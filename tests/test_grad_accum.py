"""Microbatch gradient accumulation inside the compiled step
(MXNET_GRAD_ACCUM_STEPS / FusedTrainStep(accum_steps=...)).

The step reshapes the per-device batch (B, ...) into (A, B/A, ...),
lax.scans over the A microbatches accumulating per-bucket flat gradient
buffers (a plain grads dict on the unbucketed path), and only THEN
issues the one bucketed reduce + fused update — large effective batches
under the HBM ceiling without touching the optimizer math or the
gradient-exchange schedule (ZeRO-1 rides the same reduce-scatter
layout).

Pinned with exact-arithmetic constructions (integer data, 1/4-quantized
weights, power-of-two lr/momentum/batch sizes: every intermediate is
exactly representable in fp32): the accumulated and full-batch steps
must agree BITWISE after one step on the single-device, bucketed-dp and
ZeRO-1 paths; multi-step trajectories track at float tolerance (the
update's dyadic denominators deepen past fp32 exactness at step 2+);
a non-dividing accum count is a trace-time ValueError; and a
checkpoint/resume at a step boundary — which under in-step accumulation
is ALWAYS an accumulation-window boundary, the window being atomic
inside the compiled program — replays the continuous run bitwise.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.dp import FusedTrainStep
from mxnet_tpu.parallel.mesh import make_mesh, current_device_count


def _need_devices(n):
    if current_device_count() < n:
        pytest.skip("needs %d devices" % n)


def _exact_net(seed=0):
    """BN-free Dense net with weights quantized to multiples of 1/4 —
    with {-1,0,1} inputs every product/sum below is exact in fp32."""
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8, activation="relu"),
                nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    for p in net.collect_params().values():
        w = p.data().asnumpy()
        p.set_data(nd.array(np.round(w * 4.0) / 4.0))
    return net


def _batch(n=32):
    rng = np.random.RandomState(1)
    X = nd.array(rng.randint(-1, 2, (n, 8)).astype("float32"))
    y = nd.array(rng.randint(-1, 2, (n, 4)).astype("float32"))
    return X, y


def _norm_params(net):
    """Gluon auto-naming increments prefixes across net constructions;
    normalize so two separately-built twins can be compared."""
    return {k.split("_", 1)[-1]: p.data().asnumpy()
            for k, p in net.collect_params().items()}


def _build(accum, n_dp=1, zero_stage=None, seed=0):
    net = _exact_net(seed)
    mesh = make_mesh((n_dp,), ("dp",))
    step = FusedTrainStep(net, gluon.loss.L2Loss(), mesh=mesh,
                          learning_rate=0.25, momentum=0.5,
                          weight_decay=0.0, accum_steps=accum,
                          zero_stage=zero_stage)
    return net, step


def _one_step(accum, n_dp=1, zero_stage=None):
    net, step = _build(accum, n_dp=n_dp, zero_stage=zero_stage)
    X, y = _batch()
    loss, logits = step(X, y)
    return (float(loss.asnumpy()), logits.asnumpy(), _norm_params(net))


def _assert_one_step_bitwise(n_dp, zero_stage=None):
    l1, o1, p1 = _one_step(1, n_dp=n_dp, zero_stage=zero_stage)
    l4, o4, p4 = _one_step(4, n_dp=n_dp, zero_stage=zero_stage)
    assert l1 == l4, (l1, l4)
    np.testing.assert_array_equal(o1, o4)
    assert set(p1) == set(p4)
    for k in p1:
        np.testing.assert_array_equal(p1[k], p4[k], err_msg=k)


def test_accum_matches_full_batch_single_device():
    """accum=4 over 8-image microbatches == the bs32 full-batch step,
    bitwise: loss, logits AND updated params."""
    _assert_one_step_bitwise(n_dp=1)


def test_accum_matches_full_batch_dp2_bucketed():
    """Same identity through the bucketed shard_map exchange: the accum
    scan packs per-bucket flats and the ONE reduce at the end sees
    exactly the full-batch gradient."""
    _need_devices(2)
    _assert_one_step_bitwise(n_dp=2)


def test_accum_matches_full_batch_dp2_zero1():
    """And through ZeRO-1: accumulated flats feed the same
    reduce-scatter + sharded-momentum update layout."""
    _need_devices(2)
    _assert_one_step_bitwise(n_dp=2, zero_stage=1)


def test_accum_multi_step_trajectory():
    """4-step loss trajectories track at float tolerance (the momentum
    update's dyadic denominators deepen each step, so bitwise equality
    past step 1 is not a representable claim in fp32)."""

    def traj(accum):
        _net, step = _build(accum)
        X, y = _batch()
        return [float(step(X, y)[0].asnumpy()) for _ in range(4)]

    t1, t4 = traj(1), traj(4)
    assert t1[0] == t4[0], (t1, t4)  # step 1 IS bitwise
    np.testing.assert_allclose(t1, t4, rtol=1e-6)


def test_accum_env_knob(monkeypatch):
    """MXNET_GRAD_ACCUM_STEPS is the no-code-change path: the built
    step honors the env default, and an explicit accum_steps=1
    override beats it (same precedence as every registered knob)."""
    monkeypatch.setenv("MXNET_GRAD_ACCUM_STEPS", "4")
    net, step = _build(None)
    X, y = _batch()
    l_env, _ = step(X, y)
    assert step._grad_accum == 4
    _net2, control = _build(1)
    l_ctl, _ = control(X, y)
    assert control._grad_accum == 1
    assert float(l_env.asnumpy()) == float(l_ctl.asnumpy())


def test_accum_must_divide_batch():
    """A non-dividing accum count fails loudly at trace time, not with
    a silent reshape truncation."""
    _net, step = _build(5)
    X, y = _batch(32)
    with pytest.raises(ValueError, match="does not divide"):
        step(X, y)


def test_accum_with_batchnorm_aux_dp2():
    """BN running stats thread through the accum scan carry (the last
    microbatch's stats win, matching the sequential-small-batch
    semantics) and still reach the cells."""
    _need_devices(2)
    mx.random.seed(2)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16), nn.BatchNorm(), nn.Activation("relu"),
                nn.Dense(2))
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((2,), ("dp",))
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, accum_steps=4)
    X = nd.array(np.random.RandomState(0).rand(32, 8).astype("float32"))
    y = nd.array((np.arange(32) % 2).astype("float32"))
    loss, _ = step(X, y)
    assert np.isfinite(float(loss.asnumpy()))
    rm = [p for name, p in net.collect_params().items()
          if name.endswith("running_mean")][0]
    assert float(np.abs(rm.data().asnumpy()).sum()) > 0, \
        "BN running stats must update through the accumulated step"


def test_accum_resume_at_step_boundary_bitwise():
    """Checkpoint after step 2, rebuild from scratch, restore params +
    momenta, run steps 3-4: losses and final params replay the
    uninterrupted 4-step run bitwise.  Under in-step accumulation every
    dispatch boundary is an accumulation-window boundary (the window is
    one atomic compiled program), so a step-granular checkpoint can
    never land mid-window."""
    X, y = _batch()

    net_a, step_a = _build(4)
    cont = [float(step_a(X, y)[0].asnumpy()) for _ in range(4)]
    params_cont = _norm_params(net_a)

    net_b, step_b = _build(4)
    first = [float(step_b(X, y)[0].asnumpy()) for _ in range(2)]
    np.testing.assert_array_equal(cont[:2], first)
    ckpt_params = _norm_params(net_b)
    ckpt_moms = [np.asarray(m) for m in step_b._moms]
    ckpt_ctr = step_b._key_ctr

    net_c, step_c = _build(4)
    step_c._build(X)  # build WITHOUT dispatching a step
    for k, p in net_c.collect_params().items():
        p.set_data(nd.array(ckpt_params[k.split("_", 1)[-1]]))
    step_c._moms = list(ckpt_moms)  # placed (device_put) on first call
    step_c._key_ctr = ckpt_ctr
    resumed = [float(step_c(X, y)[0].asnumpy()) for _ in range(2)]

    np.testing.assert_array_equal(cont[2:], resumed)
    params_res = _norm_params(net_c)
    for k in params_cont:
        np.testing.assert_array_equal(params_cont[k], params_res[k],
                                      err_msg=k)
