"""mx.image tests (model: tests/python/unittest/test_image.py in the
reference — synthetic images instead of downloads)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, recordio
from mxnet_tpu.ndarray import NDArray


def _synth_img(h=64, w=48, c=3, seed=0):
    # smooth gradients (JPEG-friendly), offset per seed
    yy, xx = np.mgrid[0:h, 0:w]
    chans = [(yy * (i + 1) + xx * (3 - i) + seed * 17) % 256
             for i in range(c)]
    return np.stack(chans, axis=2).astype(np.uint8)


def _jpeg_bytes(img):
    import io as _io

    from PIL import Image
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_imdecode_imread(tmp_path):
    img = _synth_img()
    raw = _jpeg_bytes(img)
    out = image.imdecode(raw)
    assert isinstance(out, NDArray)
    assert out.shape == img.shape
    # JPEG is lossy; just check it's in the ballpark
    assert np.abs(out.asnumpy().astype(np.int32) -
                  img.astype(np.int32)).mean() < 30
    gray = image.imdecode(raw, flag=0)
    assert gray.shape == (64, 48, 1)
    bgr = image.imdecode(raw, to_rgb=False)
    np.testing.assert_array_equal(bgr.asnumpy(), out.asnumpy()[:, :, ::-1])
    p = tmp_path / "x.jpg"
    p.write_bytes(raw)
    rd = image.imread(str(p))
    np.testing.assert_array_equal(rd.asnumpy(), out.asnumpy())


def test_resize_and_crops():
    img = _synth_img(100, 80)
    out = image.imresize(img, 40, 50)
    assert out.shape == (50, 40, 3)
    short = image.resize_short(img, 60)
    assert min(short.shape[:2]) == 60
    assert short.shape[0] > short.shape[1]  # aspect kept (100x80 → 75x60)
    crop = image.fixed_crop(img, 10, 20, 30, 40)
    np.testing.assert_array_equal(crop, img[20:60, 10:40])
    rc, (x0, y0, w, h) = image.random_crop(img, (32, 24))
    assert rc.shape == (24, 32, 3)
    np.testing.assert_array_equal(rc, img[y0:y0 + h, x0:x0 + w])
    cc, _ = image.center_crop(img, (32, 24))
    assert cc.shape == (24, 32, 3)
    rsc, _ = image.random_size_crop(img, (32, 32), 0.3, (0.8, 1.2))
    assert rsc.shape == (32, 32, 3)
    assert image.scale_down((30, 40), (50, 50)) == (30, 30)


def test_color_normalize_and_pad():
    img = _synth_img(8, 8)
    mean = np.array([1.0, 2.0, 3.0], np.float32)
    std = np.array([2.0, 2.0, 2.0], np.float32)
    out = image.color_normalize(img, mean, std)
    np.testing.assert_allclose(out, (img - mean) / std, rtol=1e-5)
    padded = image.copyMakeBorder(img, 1, 2, 3, 4, values=7)
    assert padded.shape == (11, 15, 3)
    assert (padded[0] == 7).all()


def test_augmenters_shapes_and_types():
    img = _synth_img(70, 60)
    augs = image.CreateAugmenter((3, 32, 32), resize=40, rand_crop=True,
                                 rand_mirror=True, mean=True, std=True,
                                 brightness=0.1, contrast=0.1,
                                 saturation=0.1, hue=0.1, pca_noise=0.1,
                                 rand_gray=0.5)
    out = img
    for a in augs:
        out = a(out)
    out = np.asarray(out)
    assert out.shape == (32, 32, 3)
    assert out.dtype == np.float32
    # every augmenter serializes
    for a in augs:
        assert a.dumps()


def test_augmenter_determinism_flip():
    img = _synth_img(10, 10)
    flip = image.HorizontalFlipAug(1.1)  # always flips
    np.testing.assert_array_equal(np.asarray(flip(img)), img[:, ::-1])
    noflip = image.HorizontalFlipAug(-0.1)
    np.testing.assert_array_equal(np.asarray(noflip(img)), img)


def _make_rec(tmp_path, n=12, label_width=1, det=False):
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        img = _synth_img(40 + i, 30 + i, seed=i)
        if det:
            # header: [header_width=2, obj_width=5] + one object
            label = np.array([2, 5, i % 4, 0.1, 0.2, 0.8, 0.9],
                             dtype=np.float32)
            header = recordio.IRHeader(0, label, i, 0)
        else:
            header = recordio.IRHeader(0, float(i % 4), i, 0)
        rec.write_idx(i, recordio.pack(header, _jpeg_bytes(img)))
    rec.close()
    return rec_path, idx_path


def test_image_iter_rec(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path)
    it = image.ImageIter(batch_size=4, data_shape=(3, 28, 28),
                         path_imgrec=rec_path, path_imgidx=idx_path,
                         shuffle=True)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 28, 28)
        assert batch.label[0].shape == (4,)
        n += 4 - batch.pad
    assert n == 12
    it.reset()
    first = next(iter(it))
    assert first.data[0].shape == (4, 3, 28, 28)


def test_image_iter_imglist(tmp_path):
    files = []
    for i in range(6):
        p = tmp_path / ("img%d.jpg" % i)
        p.write_bytes(_jpeg_bytes(_synth_img(seed=i)))
        files.append([float(i % 3), "img%d.jpg" % i])
    it = image.ImageIter(batch_size=3, data_shape=(3, 24, 24),
                         imglist=files, path_root=str(tmp_path))
    batch = next(iter(it))
    assert batch.data[0].shape == (3, 3, 24, 24)
    labels = batch.label[0].asnumpy()
    assert set(labels.tolist()) <= {0.0, 1.0, 2.0}


def test_image_iter_sharding(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path)
    it0 = image.ImageIter(batch_size=2, data_shape=(3, 16, 16),
                          path_imgrec=rec_path, path_imgidx=idx_path,
                          num_parts=2, part_index=0)
    it1 = image.ImageIter(batch_size=2, data_shape=(3, 16, 16),
                          path_imgrec=rec_path, path_imgidx=idx_path,
                          num_parts=2, part_index=1)
    assert it0.num_image == 6 and it1.num_image == 6
    assert set(it0.seq).isdisjoint(set(it1.seq))


def test_det_augmenters():
    img = _synth_img(60, 60)
    label = np.array([[0, 0.2, 0.2, 0.6, 0.7]], dtype=np.float32)
    flip = image.DetHorizontalFlipAug(1.1)
    out, lab = flip(img, label)
    np.testing.assert_array_equal(np.asarray(out), img[:, ::-1])
    np.testing.assert_allclose(lab[0, 1], 1.0 - 0.6, rtol=1e-6)
    np.testing.assert_allclose(lab[0, 3], 1.0 - 0.2, rtol=1e-6)
    crop = image.DetRandomCropAug(min_object_covered=0.1,
                                  area_range=(0.5, 1.0))
    out, lab = crop(img, label)
    assert lab.shape[1] == 5
    assert (lab[:, 1:] >= 0).all() and (lab[:, 1:] <= 1).all()
    pad = image.DetRandomPadAug(area_range=(1.5, 2.0))
    out, lab = pad(img, label)
    assert np.asarray(out).shape[0] >= 60
    augs = image.CreateDetAugmenter((3, 32, 32), rand_crop=0.5,
                                    rand_pad=0.5, rand_mirror=True,
                                    mean=True, std=True, brightness=0.1)
    out, lab = img, label
    for a in augs:
        out, lab = a(out, lab)
    assert np.asarray(out).shape == (32, 32, 3)


def test_image_det_iter(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path, det=True)
    it = image.ImageDetIter(batch_size=4, data_shape=(3, 28, 28),
                            path_imgrec=rec_path, path_imgidx=idx_path)
    assert it.label_shape[1] == 5
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 28, 28)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (4,) + it.label_shape
    # padded slots are -1
    assert (lab[:, 1:, :] == -1).all() or it.label_shape[0] == 1
    # sync_label_shape
    it2 = image.ImageDetIter(batch_size=4, data_shape=(3, 28, 28),
                             path_imgrec=rec_path, path_imgidx=idx_path)
    it2.reshape(label_shape=(5, 5))
    it.sync_label_shape(it2)
    assert it.label_shape[0] == 5


def test_recordio_pack_unpack_img():
    img = _synth_img(20, 20)
    header = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack_img(header, img, quality=95)
    h2, img2 = recordio.unpack_img(s)
    assert h2.label == 3.0
    assert img2.shape == (20, 20, 3)
    assert np.abs(img2.astype(int) - img.astype(int)).mean() < 30
    s = recordio.pack_img(header, img, img_fmt=".png")
    _, img3 = recordio.unpack_img(s)
    np.testing.assert_array_equal(img3, img)  # png lossless


def test_det_crop_sampler_properties():
    """The rewritten SSD patch sampler: area/aspect bounds hold, accepted
    patches cover every touched object, surviving boxes are clipped and
    renormalized."""
    np.random.seed(3)
    img = _synth_img(80, 120)
    label = np.array([[0, 0.1, 0.1, 0.5, 0.6],
                      [1, 0.6, 0.5, 0.9, 0.95]], dtype=np.float32)
    aug = image.DetRandomCropAug(min_object_covered=0.3,
                                 aspect_ratio_range=(0.5, 2.0),
                                 area_range=(0.3, 0.9), max_attempts=200)
    hits = 0
    for _ in range(30):
        crop = aug._sample_crop(label, 80, 120)
        if crop is None:
            continue
        hits += 1
        x, y, w, h, lab = crop
        assert 0 <= x and x + w <= 120 and 0 <= y and y + h <= 80
        frac = (w * h) / (80 * 120)
        assert 0.25 <= frac <= 0.95  # bounds with integer-rounding slack
        assert 0.4 <= w / h <= 2.1
        assert lab.shape[0] >= 1
        assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
        assert (lab[:, 3] > lab[:, 1]).all() and (lab[:, 4] > lab[:, 2]).all()
    assert hits > 0


def test_det_pad_sampler_properties():
    np.random.seed(4)
    aug = image.DetRandomPadAug(aspect_ratio_range=(0.8, 1.25),
                                area_range=(1.5, 3.0), max_attempts=100)
    label = np.array([[0, 0.25, 0.25, 0.75, 0.75]], dtype=np.float32)
    img = _synth_img(40, 40)
    out, lab = aug(img, label)
    a = np.asarray(out)
    assert a.shape[0] >= 40 and a.shape[1] >= 40
    assert a.shape[0] * a.shape[1] >= 1.4 * 40 * 40
    # boxes stay on the original image content and shrink
    assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
    w_new = lab[0, 3] - lab[0, 1]
    assert w_new < 0.5 + 1e-6


def test_contrast_jitter_preserves_mean_scale():
    """alpha=1 must be identity; the gray blend uses the true mean (the
    3x-scaled blend bug is gone)."""
    img = _synth_img(16, 16).astype(np.float32)
    aug = image.ContrastJitterAug(0.0)  # alpha == 1 always
    out = np.asarray(aug(img))
    np.testing.assert_allclose(out, img, rtol=1e-5)


def test_image_iter_roll_over(tmp_path):
    """10 images, batch 4: epoch1 yields 2 full batches and carries 2; the
    carried samples lead epoch 2's first batch (no pad anywhere)."""
    rec_path, idx_path = _make_rec(tmp_path, n=10)
    it = image.ImageIter(batch_size=4, data_shape=(3, 28, 28),
                         path_imgrec=rec_path, path_imgidx=idx_path,
                         last_batch_handle="roll_over")
    ep1 = []
    try:
        while True:
            ep1.append(it.next())
    except StopIteration:
        pass
    assert len(ep1) == 2 and all(b.pad == 0 for b in ep1)
    it.reset()
    b = it.next()
    assert b.pad == 0  # 2 carried + 2 fresh
    labels = b.label[0].asnumpy()
    assert labels.shape[0] == 4


def test_record_iter_u8_grayscale_luma_parity(tmp_path):
    """dtype='uint8' must not change what pixels a grayscale pipeline
    sees: both paths emit BT.601 luma (ref: grayscale imdecode,
    src/io/iter_image_recordio_2.cc)."""
    import numpy as np

    from mxnet_tpu import io, recordio

    rec = str(tmp_path / "g.rec")
    idx = str(tmp_path / "g.idx")
    rng = np.random.RandomState(3)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(8):
        img = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=100))
    w.close()

    def batch(dtype):
        it = io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, data_shape=(1, 32, 32),
            batch_size=8, shuffle=False, preprocess_threads=1, dtype=dtype)
        return it.next().data[0].asnumpy()

    f32 = batch("float32")
    u8 = batch("uint8").astype(np.float32)
    assert np.abs(f32 - u8).max() <= 1.0  # rounding only
