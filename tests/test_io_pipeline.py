"""Input-pipeline rearchitecture tests: sharded multi-process decode
pool + double-buffered async device prefetch (mxnet_tpu/io_pipeline.py).

The heavy lifecycle proofs (determinism, worker death, slow_decode
chaos, SIGTERM shared-memory hygiene) live in the module's own
``--self-test`` CLI and run here once as a subprocess; the in-process
tests cover the integration seams: per-iterator sharding coverage, the
device stage feeding a fused train step with donation-safe batches,
the io telemetry (queue depth gauge, decode histogram, io:* trace
lanes + overlap analysis), the compile-cache knob, and the MXL007
decode-worker lint."""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io_pipeline as iop

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _child_env():
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": ROOT + os.pathsep +
                env.get("PYTHONPATH", "")})
    env.pop("MXNET_CHAOS", None)
    return env


# ---------------------------------------------------------------------
# satellite: num_parts/part_index on every batch iterator — disjoint
# and exhaustive coverage across parts
# ---------------------------------------------------------------------
def _collect_ids(make_part, parts):
    """Label ids per part, unpadded."""
    out = []
    for p in range(parts):
        it = make_part(parts, p)
        ids = []
        while True:
            try:
                b = it.next()
            except StopIteration:
                break
            lab = b.label[0].asnumpy().reshape(-1)
            keep = len(lab) - b.pad
            ids.extend(int(v) for v in lab[:keep])
        out.append(ids)
    return out


def test_ndarray_iter_sharding_disjoint_exhaustive():
    x = np.arange(60, dtype=np.float32).reshape(30, 2)
    y = np.arange(30, dtype=np.float32)
    per_part = _collect_ids(
        lambda n, p: mx.io.NDArrayIter(x, y, batch_size=4, num_parts=n,
                                       part_index=p), 3)
    flat = [v for part in per_part for v in part]
    assert sorted(flat) == list(range(30))          # exhaustive
    assert len(flat) == len(set(flat))              # disjoint
    # strided slices, like MNISTIter
    assert per_part[1][:3] == [1, 4, 7]


def test_csv_iter_sharding(tmp_path):
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    label = np.arange(12, dtype=np.float32)
    dcsv, lcsv = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    np.savetxt(dcsv, data, delimiter=",")
    np.savetxt(lcsv, label, delimiter=",")
    per_part = _collect_ids(
        lambda n, p: mx.io.CSVIter(data_csv=dcsv, data_shape=(2,),
                                   label_csv=lcsv, label_shape=(1,),
                                   batch_size=3, num_parts=n,
                                   part_index=p), 2)
    flat = [v for part in per_part for v in part]
    assert sorted(flat) == list(range(12)) and len(flat) == 12


def test_image_record_iter_sharding(tmp_path):
    from mxnet_tpu import recordio

    rec, idx = str(tmp_path / "a.rec"), str(tmp_path / "a.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(12):
        img = rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=90))
    w.close()
    per_part = _collect_ids(
        lambda n, p: mx.io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, data_shape=(3, 28, 28),
            batch_size=2, num_parts=n, part_index=p, dtype="uint8",
            shuffle=False), 3)
    flat = [v for part in per_part for v in part]
    assert sorted(flat) == list(range(12)) and len(flat) == 12
    assert per_part[0] == [0, 3, 6, 9]
    # no leaked temp shard files: __del__ removes the part copies
    import gc

    gc.collect()


def test_mnist_iter_next_raw_matches_next():
    it = mx.io.MNISTIter(batch_size=50, shuffle=False, num_parts=2,
                         part_index=0)
    data, label, pad = it.next_raw()
    assert data[0].shape == (50, 1, 28, 28) and pad == 0
    it2 = mx.io.MNISTIter(batch_size=50, shuffle=False, num_parts=2,
                          part_index=0)
    b = it2.next()
    np.testing.assert_array_equal(data[0], b.data[0].asnumpy())


# ---------------------------------------------------------------------
# the pool + device stage (in-process)
# ---------------------------------------------------------------------
def test_pipeline_stream_deterministic_and_complete():
    x = np.arange(64, dtype=np.float32).reshape(32, 2)
    y = np.arange(32, dtype=np.float32)
    fn = iop.make_ndarray_iter_fn(x, y, batch_size=4,
                                  last_batch_handle="discard")
    with iop.InputPipeline(fn, num_workers=2, device=False) as pipe:
        assert pipe.batch_size == 4
        assert pipe.provide_data[0].shape == (4, 2)
        e1 = []
        while True:
            try:
                b = pipe.next()
            except StopIteration:
                break
            e1.extend(int(v) for v in b.label[0].asnumpy())
        assert sorted(e1) == list(range(32))
        # worker 0 owns [0,2,4..], worker 1 [1,3,5..]; round-robin
        assert e1[:8] == [0, 2, 4, 6, 1, 3, 5, 7]
        pipe.reset()
        e2 = []
        while True:
            try:
                b = pipe.next()
            except StopIteration:
                break
            e2.extend(int(v) for v in b.label[0].asnumpy())
        assert e2 == e1
        assert pipe.cursor == 32


def test_device_prefetch_feeds_fused_step():
    """The tentpole integration: pool -> async device_put -> donated
    fused steps, with io:* spans on per-worker lanes and the overlap
    analyzer consuming the dump."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import diagnostics as diag
    from mxnet_tpu import gluon, profiler
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, 64).astype(np.float32)
    fn = iop.make_ndarray_iter_fn(x, y, batch_size=8,
                                  last_batch_handle="discard")
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((1,), ("dp",), jax.devices()[:1])
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh)
    profiler.set_state("run")
    try:
        with iop.InputPipeline(fn, num_workers=2, device=True) as pipe:
            losses = None
            bd, bl = [], []
            while True:
                try:
                    b = pipe.next()
                except StopIteration:
                    break
                arr = b.data[0]._data
                assert hasattr(arr, "devices")  # device-committed
                bd.append(arr)
                bl.append(b.label[0]._data)
                if len(bd) == 4:
                    sd, sl = jnp.stack(bd), jnp.stack(bl)
                    iop.mark_disposable(sd)
                    iop.mark_disposable(sl)
                    losses = step.run_steps(sd, sl)
                    bd, bl = [], []
            assert losses is not None
            assert np.isfinite(losses.asnumpy()).all()
        events = [dict(e) for e in profiler._events]
    finally:
        profiler.set_state("stop")
    names = {e["name"] for e in events if e.get("ph") == "X"}
    assert "io:decode" in names and "io:device_put" in names \
        and "io:wait" in names
    assert any("run_steps" in n for n in names)
    # decode spans ride per-worker lanes at the reserved tid base
    lanes = {e["tid"] for e in events if e.get("name") == "io:decode"}
    assert lanes <= {iop.IO_WORKER_TID_BASE, iop.IO_WORKER_TID_BASE + 1}
    assert len(lanes) >= 1
    # overlap analyzer consumes the span families
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import merge_traces as mt
    finally:
        sys.path.pop(0)
    rep = mt.analyze_io_overlap({0: {"traceEvents": events}})
    assert rep and rep[0]["n_io_spans"] > 0 and rep[0]["n_step_spans"] > 0
    assert 0.0 <= rep[0]["prefetch_overlap_frac"] <= 1.0
    # metrics registry fed: queue depth gauge + decode-time histogram
    assert diag.metrics.gauge("mxnet_io_queue_depth").value is not None
    h = diag.metrics.histogram("mxnet_io_decode_seconds")
    assert h.count > 0


def test_donate_safe_put_disposable_handoff():
    """A pipeline-owned (disposable) array donates as-is; a caller-owned
    one still gets the defensive copy."""
    import jax
    from jax.sharding import SingleDeviceSharding

    from mxnet_tpu.parallel.dp import _donate_safe_put

    dev = jax.devices()[0]
    sh = SingleDeviceSharding(dev)
    a = jax.device_put(np.ones((4, 4), np.float32), dev)
    iop.mark_disposable(a)
    assert _donate_safe_put(jax, a, sh) is a
    # the mark is one-shot: a second donate of the same array copies
    assert _donate_safe_put(jax, a, sh) is not a
    b = jax.device_put(np.ones((4, 4), np.float32), dev)
    placed = _donate_safe_put(jax, b, sh)
    assert placed is not b


def test_skip_batches_matches_consumed_stream():
    """skip_batches(n) lands the stream at exactly the position n
    next() calls would (the exact-resume fast path)."""
    x = np.arange(64, dtype=np.float32).reshape(32, 2)
    y = np.arange(32, dtype=np.float32)
    fn = iop.make_ndarray_iter_fn(x, y, batch_size=4,
                                  last_batch_handle="discard")
    with iop.InputPipeline(fn, num_workers=2, device=False) as p1:
        seq = []
        while True:
            try:
                seq.append([int(v) for v in p1.next().label[0].asnumpy()])
            except StopIteration:
                break
    with iop.InputPipeline(fn, num_workers=2, device=False) as p2:
        p2.skip_batches(3)
        assert p2.cursor == 12
        nxt = [int(v) for v in p2.next().label[0].asnumpy()]
        assert nxt == seq[3]


def test_self_test_cli():
    """The tier-1 wiring for the pool's lifecycle proofs: start/stop/
    drain, determinism, worker death, slow_decode chaos, device stage,
    SIGTERM shared-memory hygiene."""
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.io_pipeline", "--self-test"],
        capture_output=True, text=True, env=_child_env(), cwd=ROOT,
        timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    payload = json.loads(res.stdout.strip().splitlines()[-1])
    assert payload["self_test_ok"], payload


# ---------------------------------------------------------------------
# satellite: persistent compile cache knob
# ---------------------------------------------------------------------
def test_compile_cache_helper(monkeypatch, tmp_path):
    import jax

    from mxnet_tpu import compile_cache

    monkeypatch.delenv("MXNET_COMPILE_CACHE_DIR", raising=False)
    # explicit argument beats the (unset) env
    d = str(tmp_path / "cc")
    assert compile_cache.enable(d) == os.path.abspath(d)
    assert jax.config.jax_compilation_cache_dir == os.path.abspath(d)
    assert compile_cache.enabled_dir() == os.path.abspath(d)
    # idempotent
    assert compile_cache.enable(d) == os.path.abspath(d)
    # env-driven spelling
    d2 = str(tmp_path / "cc2")
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", d2)
    assert compile_cache.enable() == os.path.abspath(d2)
    assert os.path.isdir(d2)


def test_compile_cache_unset_is_noop(monkeypatch):
    from mxnet_tpu import compile_cache

    monkeypatch.delenv("MXNET_COMPILE_CACHE_DIR", raising=False)
    assert compile_cache.enable() is None


# ---------------------------------------------------------------------
# satellite: MXL007 — jax/device calls inside decode-worker functions
# ---------------------------------------------------------------------
def test_mxlint_mxl007_flags_worker_jax():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxlint
    finally:
        sys.path.pop(0)
    src = (
        "import jax\n"
        "def _decode_worker_main(q):\n"
        "    x = q.get()\n"
        "    jax.device_put(x)\n"
        "def host_side(x):\n"
        "    return jax.device_put(x)\n"
        "def my_factory(num_parts=1, part_index=0):\n"
        "    return jax.numpy.zeros(())\n"
        "def boot():\n"
        "    return InputPipeline(iter_fn=my_factory)\n"
    )
    registered, import_ok = mxlint.registered_env_names()
    linter = mxlint.ModuleLinter("<t>.py", src, registered, import_ok,
                                 is_env_py=False)
    found = [(f["code"], f["scope"]) for f in linter.run()]
    assert ("MXL007", "_decode_worker_main") in found
    assert ("MXL007", "my_factory") in found  # iter_fn= callee flagged
    # jax on the HOST side (device stage, bench loops) stays legal
    assert not any(s == "host_side" for c, s in found if c == "MXL007")


def test_mxlint_repo_has_no_mxl007():
    """The shipped decode worker itself honors the host-only contract."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxlint
    finally:
        sys.path.pop(0)
    registered, import_ok = mxlint.registered_env_names()
    findings = mxlint.lint_paths(
        [os.path.join(ROOT, "mxnet_tpu", "io_pipeline.py")],
        registered, import_ok)
    assert not [f for f in findings if f["code"] == "MXL007"], findings


# ---------------------------------------------------------------------
# new env knobs are registered (mxlint MXL001 would also catch reads)
# ---------------------------------------------------------------------
def test_io_env_knobs_registered():
    from mxnet_tpu import env

    for name in ("MXNET_IO_WORKERS", "MXNET_IO_PREFETCH_DEPTH",
                 "MXNET_IO_POOL_SLOTS", "MXNET_IO_START_METHOD",
                 "MXNET_COMPILE_CACHE_DIR"):
        assert env.is_registered(name), name


# ---------------------------------------------------------------------
# elastic heartbeat coverage (ISSUE 15 satellite): the parent's decode
# wait beacons liveness — a supervised run starved behind slow decode
# workers must not be SIGKILLed as "hung"
# ---------------------------------------------------------------------
def test_io_wait_touches_heartbeat(tmp_path, monkeypatch):
    from mxnet_tpu import chaos as chaos_mod
    from mxnet_tpu import diagnostics as diag

    hb_dir = str(tmp_path / "hb")
    monkeypatch.setenv("MXNET_ELASTIC_HEARTBEAT_DIR", hb_dir)
    # a seeded straggler: every batch from worker 0 arrives ~0.6s late,
    # so the parent's fetch loop spins its Empty branch
    monkeypatch.setenv("MXNET_CHAOS",
                       "slow_decode:worker=0,ms=600,count=100")
    chaos_mod.reset()
    monkeypatch.setattr(diag, "_hb_last", 0.0)
    monkeypatch.setattr(diag, "_hb_path", None)
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    y = np.arange(16, dtype=np.float32)
    fn = iop.make_ndarray_iter_fn(x, y, batch_size=4,
                                  last_batch_handle="discard")
    pool = iop.ShardedDecodePool(fn, num_workers=1)
    try:
        b = pool.next()
        assert b is not None
        assert os.path.exists(os.path.join(hb_dir, "hb_rank0")), \
            os.listdir(hb_dir) if os.path.isdir(hb_dir) else "no hb"
    finally:
        pool.close()
        chaos_mod.reset()
