"""Reference-checkpoint interop: legacy symbol JSON + dmlc .params.

ref: src/nnvm/legacy_json_util.cc (JSON upgrade chain),
src/ndarray/ndarray.cc:860-1100 (the .params container layout),
python/mxnet/model.py:396 (load_checkpoint).

The fixtures are built the way the *reference* would build them — JSON
with all-string attrs under version-appropriate containers, and a
byte-level dmlc container written here by an independent packer — so a
real model-zoo checkpoint follows the same path."""
import json
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import utils as nd_utils


# ---------------------------------------------------------------------------
# independent reference-layout packer (mirrors ndarray.cc Save, written
# from the format spec, NOT via mxnet_tpu's writer — so reader bugs
# can't cancel writer bugs)
# ---------------------------------------------------------------------------

def _pack_shape(shape):
    out = struct.pack("<I", len(shape))
    for d in shape:
        out += struct.pack("<q", d)
    return out


def _pack_dense_v2(a):
    flag = {"float32": 0, "float64": 1, "uint8": 3,
            "int32": 4, "int64": 6}[str(a.dtype)]
    out = struct.pack("<I", 0xF993FAC9)          # V2 magic
    out += struct.pack("<i", 0)                   # dense storage
    out += _pack_shape(a.shape)
    out += struct.pack("<ii", 1, 0)               # cpu(0) context
    out += struct.pack("<i", flag)
    out += np.ascontiguousarray(a).tobytes()
    return out


def _pack_dense_v1(a):
    out = struct.pack("<I", 0xF993FAC8)           # V1 magic
    out += _pack_shape(a.shape)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", 0)
    return out + np.ascontiguousarray(a.astype(np.float32)).tobytes()


def _pack_dense_legacy(a):
    # pre-V1: leading uint32 is ndim, dims are uint32
    out = struct.pack("<I", len(a.shape))
    for d in a.shape:
        out += struct.pack("<I", d)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", 0)
    return out + np.ascontiguousarray(a.astype(np.float32)).tobytes()


def _pack_container(named, packer=_pack_dense_v2):
    out = struct.pack("<QQ", 0x112, 0)
    out += struct.pack("<Q", len(named))
    for _, a in named:
        out += packer(a)
    out += struct.pack("<Q", len(named))
    for name, _ in named:
        b = name.encode()
        out += struct.pack("<Q", len(b)) + b
    return out


@pytest.mark.parametrize("packer", [_pack_dense_v2, _pack_dense_v1,
                                    _pack_dense_legacy])
def test_params_container_reads_all_versions(tmp_path, packer):
    rng = np.random.RandomState(0)
    w = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    blob = _pack_container([("arg:w", w), ("arg:b", b)], packer)
    p = tmp_path / "ref.params"
    p.write_bytes(blob)
    loaded = nd_utils.load(str(p))
    np.testing.assert_allclose(loaded["arg:w"].asnumpy(), w, rtol=1e-6)
    np.testing.assert_allclose(loaded["arg:b"].asnumpy(), b, rtol=1e-6)


def test_params_container_roundtrip_dmlc_writer(tmp_path):
    """Our writer produces the same container our reference-layout
    reader (and therefore the reference) parses."""
    rng = np.random.RandomState(1)
    data = {"a": nd.array(rng.randn(2, 5).astype(np.float32)),
            "b": nd.array(rng.randint(0, 9, (3,)).astype(np.int32))}
    p = str(tmp_path / "rt.params")  # .params => dmlc format by default
    nd_utils.save(p, data)
    with open(p, "rb") as f:
        assert struct.unpack("<Q", f.read(8))[0] == 0x112
    out = nd_utils.load(p)
    np.testing.assert_allclose(out["a"].asnumpy(),
                               data["a"].asnumpy(), rtol=1e-6)
    np.testing.assert_array_equal(out["b"].asnumpy(),
                                  data["b"].asnumpy())
    assert out["b"].asnumpy().dtype == np.int32


def _legacy_mlp_json():
    """An MLP the way a 1.x reference save looks: attrs all strings,
    cudnn/workspace knobs present, 2-element head entries."""
    nodes = [
        {"op": "null", "name": "data", "inputs": []},
        {"op": "null", "name": "fc1_weight",
         "attrs": {"lr_mult": "2.0"}, "inputs": []},
        {"op": "null", "name": "fc1_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc1",
         "attrs": {"num_hidden": "8", "no_bias": "False"},
         "inputs": [[0, 0], [1, 0], [2, 0]]},
        {"op": "Activation", "name": "relu1",
         "attrs": {"act_type": "relu"}, "inputs": [[3, 0]]},
        {"op": "null", "name": "fc2_weight", "inputs": []},
        {"op": "null", "name": "fc2_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc2",
         "attrs": {"num_hidden": "3"},
         "inputs": [[4, 0], [5, 0], [6, 0]]},
    ]
    return json.dumps({
        "nodes": nodes,
        "arg_nodes": [0, 1, 2, 5, 6],
        "node_row_ptr": list(range(9)),
        "heads": [[7, 0]],
        "attrs": {"mxnet_version": ["int", 10100]},
    })


def test_legacy_json_loads_and_matches_native_logits(tmp_path):
    sym = mx.sym.load_json(_legacy_mlp_json())
    assert sym.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias"]
    rng = np.random.RandomState(2)
    args = {
        "data": nd.array(rng.randn(5, 7).astype(np.float32)),
        "fc1_weight": nd.array(rng.randn(8, 7).astype(np.float32)),
        "fc1_bias": nd.array(rng.randn(8).astype(np.float32)),
        "fc2_weight": nd.array(rng.randn(3, 8).astype(np.float32)),
        "fc2_bias": nd.array(rng.randn(3).astype(np.float32)),
    }
    out = sym.bind(args=dict(args)).forward()[0].asnumpy()

    # natively-built ground truth
    d = mx.sym.Variable("data")
    n = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    n = mx.sym.Activation(n, act_type="relu", name="relu1")
    n = mx.sym.FullyConnected(n, num_hidden=3, name="fc2")
    want = n.bind(args=dict(args)).forward()[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    # hidden key moved out of op params into __lr_mult__ on the var
    loaded = json.loads(sym.tojson())
    w1 = [nd_ for nd_ in loaded["nodes"] if nd_["name"] == "fc1_weight"][0]
    assert w1["attrs"].get("__lr_mult__") == "2.0"


def _pre09_json():
    """0.8-era graph: ``param`` container, parameter inputs omitted
    (the saver relied on runtime materialization)."""
    nodes = [
        {"op": "null", "name": "data", "param": {}, "inputs": []},
        {"op": "FullyConnected", "name": "fc",
         "param": {"num_hidden": "4"}, "inputs": [[0, 0]]},
        {"op": "BatchNorm", "name": "bn",
         "param": {"eps": "0.001", "momentum": "0.9",
                   "fix_gamma": "True"},
         "inputs": [[1, 0]]},
    ]
    return json.dumps({
        "nodes": nodes,
        "arg_nodes": [0],
        "heads": [[2, 0]],
        # no mxnet_version attr => treated as pre-0.9
    })


def test_pre09_json_materializes_missing_inputs():
    sym = mx.sym.load_json(_pre09_json())
    args = sym.list_arguments()
    # fc weight/bias and bn gamma/beta materialized with reference names
    assert args == ["data", "fc_weight", "fc_bias", "bn_gamma", "bn_beta"]
    aux = sym.list_auxiliary_states()
    assert aux == ["bn_moving_mean", "bn_moving_var"]
    # and the graph runs
    rng = np.random.RandomState(3)
    ex = sym.simple_bind(data=(2, 6))
    for name, arr in ex.arg_dict.items():
        if name != "data":
            arr[:] = rng.randn(*arr.shape).astype(np.float32) * 0.1
    ex.arg_dict["data"][:] = rng.randn(2, 6).astype(np.float32)
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 4)
    assert np.isfinite(out).all()


def test_full_checkpoint_roundtrip_reference_format(tmp_path):
    """save_checkpoint -> files in the reference's on-disk formats ->
    load_checkpoint -> identical logits."""
    d = mx.sym.Variable("data")
    n = mx.sym.FullyConnected(d, num_hidden=6, name="f1")
    n = mx.sym.Activation(n, act_type="tanh", name="t")
    n = mx.sym.FullyConnected(n, num_hidden=2, name="f2")

    rng = np.random.RandomState(4)
    arg_params = {
        "f1_weight": nd.array(rng.randn(6, 4).astype(np.float32)),
        "f1_bias": nd.zeros((6,)),
        "f2_weight": nd.array(rng.randn(2, 6).astype(np.float32)),
        "f2_bias": nd.zeros((2,)),
    }
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 3, n, arg_params, {})
    # .params is a dmlc container (reference tools can read it)
    with open(prefix + "-0003.params", "rb") as f:
        assert struct.unpack("<Q", f.read(8))[0] == 0x112

    sym2, args2, aux2 = mx.model.load_checkpoint(prefix, 3)
    x = nd.array(rng.randn(3, 4).astype(np.float32))
    args = dict(args2)
    args["data"] = x
    out = sym2.bind(args=args).forward()[0].asnumpy()
    wargs = dict(arg_params)
    wargs["data"] = x
    want = n.bind(args=wargs).forward()[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_auto_format_preserves_unrepresentable_payloads(tmp_path):
    """bf16 and 0-d payloads must not be silently widened/dropped by
    the .params container default — they keep the lossless npz path."""
    p = str(tmp_path / "w.params")
    scalar = nd.array(np.float32(3.25)).reshape(())
    nd_utils.save(p, {"s": scalar})
    back = nd_utils.load(p)
    assert back["s"].shape == ()
    np.testing.assert_allclose(back["s"].asnumpy(), 3.25)

    bf = nd.array(np.ones((2, 2), np.float32)).astype("bfloat16")
    nd_utils.save(p, {"w": bf})
    back = nd_utils.load(p)
    assert str(back["w"].dtype) == "bfloat16"
