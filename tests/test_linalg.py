"""linalg op tests (modelled on tests/python/unittest/test_operator.py's
test_laop* — forward numerics against numpy + finite-difference gradients)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def _rand_spd(n, batch=(), dtype="float64"):
    a = np.random.rand(*batch, n, n).astype(dtype)
    return np.matmul(a, np.swapaxes(a, -1, -2)) + n * np.eye(n, dtype=dtype)


def test_gemm():
    A = np.random.rand(2, 3, 4).astype("float64")
    B = np.random.rand(2, 4, 5).astype("float64")
    C = np.random.rand(2, 3, 5).astype("float64")
    out = nd.linalg.gemm(nd.array(A), nd.array(B), nd.array(C), alpha=2.0, beta=0.5)
    assert_almost_equal(out, 2.0 * A @ B + 0.5 * C, rtol=1e-8, atol=1e-8)
    out = nd.linalg.gemm(
        nd.array(np.swapaxes(A, -1, -2)), nd.array(B), nd.array(C), transpose_a=True
    )
    assert_almost_equal(out, A @ B + C, rtol=1e-8, atol=1e-8)


def test_gemm2_grad():
    A = nd.array(np.random.rand(3, 4).astype("float64"))
    B = nd.array(np.random.rand(4, 2).astype("float64"))
    check_numeric_gradient(lambda a, b: nd.linalg.gemm2(a, b, alpha=1.5), [A, B])


def test_potrf_potri_sumlogdiag():
    S = _rand_spd(4, batch=(2,))
    L = nd.linalg.potrf(nd.array(S))
    assert_almost_equal(np.matmul(L.asnumpy(), np.swapaxes(L.asnumpy(), -1, -2)), S,
                        rtol=1e-6, atol=1e-6)
    Sinv = nd.linalg.potri(L)
    assert_almost_equal(np.matmul(Sinv.asnumpy(), S),
                        np.broadcast_to(np.eye(4), S.shape), rtol=1e-6, atol=1e-6)
    sld = nd.linalg.sumlogdiag(L)
    assert_almost_equal(sld, np.sum(np.log(np.diagonal(L.asnumpy(), axis1=-2, axis2=-1)),
                                    axis=-1), rtol=1e-6, atol=1e-6)


def test_potrf_grad():
    S = nd.array(_rand_spd(3))
    check_numeric_gradient(lambda a: nd.linalg.potrf(a), [S], eps=1e-6)


def test_trmm_trsm():
    A = np.tril(np.random.rand(4, 4) + np.eye(4) * 4).astype("float64")
    B = np.random.rand(4, 3).astype("float64")
    out = nd.linalg.trmm(nd.array(A), nd.array(B), alpha=2.0)
    assert_almost_equal(out, 2.0 * A @ B, rtol=1e-8, atol=1e-8)
    X = nd.linalg.trsm(nd.array(A), nd.array(A @ B))
    assert_almost_equal(X, B, rtol=1e-6, atol=1e-6)
    # rightside: X @ A = alpha * B
    Br = np.random.rand(3, 4).astype("float64")
    Xr = nd.linalg.trsm(nd.array(A), nd.array(Br @ A), rightside=True)
    assert_almost_equal(Xr, Br, rtol=1e-6, atol=1e-6)


def test_syrk():
    A = np.random.rand(2, 3, 4).astype("float64")
    out = nd.linalg.syrk(nd.array(A), alpha=0.5)
    assert_almost_equal(out, 0.5 * A @ np.swapaxes(A, -1, -2), rtol=1e-8, atol=1e-8)
    out_t = nd.linalg.syrk(nd.array(A), transpose=True)
    assert_almost_equal(out_t, np.swapaxes(A, -1, -2) @ A, rtol=1e-8, atol=1e-8)


def test_gelqf():
    A = np.random.rand(3, 5).astype("float64")
    Q, L = nd.linalg.gelqf(nd.array(A))
    assert_almost_equal(L.asnumpy() @ Q.asnumpy(), A, rtol=1e-6, atol=1e-6)
    assert_almost_equal(Q.asnumpy() @ Q.asnumpy().T, np.eye(3), rtol=1e-6, atol=1e-6)
    # L lower triangular
    assert_almost_equal(np.triu(L.asnumpy(), k=1), np.zeros((3, 3)), rtol=0, atol=1e-12)


def test_syevd():
    S = _rand_spd(4)
    U, lam = nd.linalg.syevd(nd.array(S))
    Un, ln = U.asnumpy(), lam.asnumpy()
    # MXNet convention: A = U^T diag(lam) U (rows of U are eigenvectors)
    assert_almost_equal(Un.T @ np.diag(ln) @ Un, S, rtol=1e-6, atol=1e-6)


def test_makediag_extractdiag():
    v = np.random.rand(2, 3).astype("float64")
    D = nd.linalg.makediag(nd.array(v))
    assert D.shape == (2, 3, 3)
    assert_almost_equal(nd.linalg.extractdiag(D), v, rtol=0, atol=0)
    D1 = nd.linalg.makediag(nd.array(v), offset=1)
    assert D1.shape == (2, 4, 4)
    assert_almost_equal(nd.linalg.extractdiag(D1, offset=1), v, rtol=0, atol=0)


def test_maketrian_extracttrian():
    A = np.tril(np.random.rand(3, 3)).astype("float64")
    v = nd.linalg.extracttrian(nd.array(A))
    assert v.shape == (6,)
    back = nd.linalg.maketrian(v)
    assert_almost_equal(back, A, rtol=0, atol=0)
    # positive offset selects the upper band regardless of `lower`
    M = np.arange(9, dtype="float64").reshape(3, 3)
    vu = nd.linalg.extracttrian(nd.array(M), offset=1)
    assert_almost_equal(vu, np.array([1.0, 2.0, 5.0]), rtol=0, atol=0)
    vl = nd.linalg.extracttrian(nd.array(M), offset=-1)
    assert_almost_equal(vl, np.array([3.0, 6.0, 7.0]), rtol=0, atol=0)
    bu = nd.linalg.maketrian(vu, offset=1).asnumpy()
    assert_almost_equal(bu, np.array([[0, 1, 2], [0, 0, 5], [0, 0, 0]],
                                     dtype="float64"), rtol=0, atol=0)


def test_inverse_det_slogdet():
    A = _rand_spd(3)
    Ainv = nd.linalg.inverse(nd.array(A))
    assert_almost_equal(Ainv.asnumpy() @ A, np.eye(3), rtol=1e-6, atol=1e-6)
    d = nd.linalg.det(nd.array(A))
    assert_almost_equal(d, np.linalg.det(A), rtol=1e-6, atol=1e-6)
    sign, logabs = nd.linalg.slogdet(nd.array(A))
    s_np, l_np = np.linalg.slogdet(A)
    assert_almost_equal(sign, s_np, rtol=1e-6, atol=1e-6)
    assert_almost_equal(logabs, l_np, rtol=1e-6, atol=1e-6)


def test_symbol_linalg():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.linalg.gemm2(a, b)
    ex = out.bind(mx.cpu(), {"a": nd.array(np.random.rand(3, 4)),
                             "b": nd.array(np.random.rand(4, 2))})
    y = ex.forward()[0]
    assert y.shape == (3, 2)
