"""Model parallelism via ctx_group (ref: tests/python/unittest/
test_model_parallel.py, src/executor/graph_executor.cc:406 PlaceDevice).

Runs on the 8-device virtual CPU mesh from conftest: ctx groups map to
distinct virtual devices, cross-group values move via device_put (the
cross_device_copy analogue)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _dev_of(arr):
    (dev,) = arr._data.devices()
    return dev


def test_chain_forward_backward_matches_single_device():
    # reference test_model_parallel.py test_chain, adapted shapes
    n, m = 4, 5
    data1 = sym.Variable("data1")
    data2 = sym.Variable("data2")
    data3 = sym.Variable("data3")
    with sym.AttrScope(ctx_group="dev1"):
        net = data1 + data2
        net = net * 3.0
    with sym.AttrScope(ctx_group="dev2"):
        net = net + data3

    arr = [mx.nd.ones((n, m)) * (i + 1) for i in range(3)]
    arr_grad = [mx.nd.zeros((n, m)) for _ in range(3)]

    exec1 = net.bind(mx.cpu(),
                     args=dict(zip(["data1", "data2", "data3"], arr)),
                     args_grad=dict(zip(["data1", "data2", "data3"], arr_grad)),
                     group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    out1 = exec1.forward(is_train=True)[0].asnumpy()
    exec1.backward([mx.nd.ones((n, m)) * 2.0])

    # single-device reference run
    arr_s = [mx.nd.ones((n, m)) * (i + 1) for i in range(3)]
    grad_s = [mx.nd.zeros((n, m)) for _ in range(3)]
    exec2 = net.bind(mx.cpu(),
                     args=dict(zip(["data1", "data2", "data3"], arr_s)),
                     args_grad=dict(zip(["data1", "data2", "data3"], grad_s)))
    out2 = exec2.forward(is_train=True)[0].asnumpy()
    exec2.backward([mx.nd.ones((n, m)) * 2.0])

    np.testing.assert_allclose(out1, out2, rtol=1e-6)
    np.testing.assert_allclose(out1, ((1 + 2) * 3.0 + 3) * np.ones((n, m)))
    for g1, g2 in zip(arr_grad, grad_s):
        np.testing.assert_allclose(g1.asnumpy(), g2.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(arr_grad[0].asnumpy(), 2 * 3.0 * np.ones((n, m)))


def test_placement_is_real():
    """Args land on their group's device; ungrouped stay on default."""
    import jax

    devs = jax.devices()
    a = sym.Variable("a")
    b = sym.Variable("b")
    with sym.AttrScope(ctx_group="dev1"):
        h = sym.FullyConnected(a, num_hidden=8, name="fc1")
    with sym.AttrScope(ctx_group="dev2"):
        out = sym.FullyConnected(h + b, num_hidden=4, name="fc2")

    ex = out.simple_bind(mx.cpu(0), a=(2, 16), b=(2, 8),
                         group2ctx={"dev1": mx.cpu(2), "dev2": mx.cpu(3)})
    assert _dev_of(ex.arg_dict["fc1_weight"]) == devs[2]
    assert _dev_of(ex.arg_dict["a"]) == devs[2]
    assert _dev_of(ex.arg_dict["fc2_weight"]) == devs[3]
    outs = ex.forward(is_train=True)
    assert outs[0].shape == (2, 4)
    ex.backward([mx.nd.ones((2, 4))])
    # gradients come back on the argument's device
    assert _dev_of(ex.grad_dict["fc1_weight"]) == devs[2]


def test_variable_own_ctx_group_wins():
    """A ctx_group set on the Variable itself overrides consumer
    inheritance (reference PlaceDevice honors the node's own group)."""
    import jax

    devs = jax.devices()
    with sym.AttrScope(ctx_group="wgroup"):
        w = sym.Variable("w")
    x = sym.Variable("x")
    with sym.AttrScope(ctx_group="opgroup"):
        out = sym.dot(x, w)
    ex = out.simple_bind(mx.cpu(0), x=(3, 4), w=(4, 5),
                         group2ctx={"wgroup": mx.cpu(4),
                                    "opgroup": mx.cpu(5)})
    assert _dev_of(ex.arg_dict["w"]) == devs[4]
    assert _dev_of(ex.arg_dict["x"]) == devs[5]


def test_monitor_on_placed_executor():
    """Monitor taps work on a model-parallel executor (no jit over
    mixed-device inputs)."""
    a = sym.Variable("a")
    with sym.AttrScope(ctx_group="dev1"):
        h = sym.FullyConnected(a, num_hidden=4, name="fcm1")
    with sym.AttrScope(ctx_group="dev2"):
        o = sym.Activation(h, act_type="tanh", name="actm")
    ex = o.simple_bind(mx.cpu(0), a=(2, 3),
                       group2ctx={"dev1": mx.cpu(1), "dev2": mx.cpu(2)})
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(is_train=True)
    assert any(n.startswith("fcm1") for n in seen)
    assert any(n.startswith("actm") for n in seen)


def test_module_group2ctxs_trains():
    """Reference example/model-parallel style: an MLP split over two
    groups trains through Module with numerics matching the unplaced run."""
    np.random.seed(0)
    mx.random.seed(0)
    N, D, H, C = 32, 10, 16, 3
    X = np.random.randn(N, D).astype("float32")
    W = np.random.randn(D, C)
    y = X @ W
    y = y.argmax(axis=1).astype("float32")

    def build():
        data = sym.Variable("data")
        with sym.AttrScope(ctx_group="dev1"):
            h = sym.Activation(
                sym.FullyConnected(data, num_hidden=H, name="fc1"),
                act_type="relu")
        with sym.AttrScope(ctx_group="dev2"):
            logits = sym.FullyConnected(h, num_hidden=C, name="fc2")
        return sym.SoftmaxOutput(logits, sym.Variable("softmax_label"),
                                 name="softmax")

    def train(group2ctxs):
        np.random.seed(0)
        mx.random.seed(0)
        mod = mx.mod.Module(build(), context=mx.cpu(0),
                            group2ctxs=group2ctxs)
        it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
        mod.fit(it, num_epoch=10,
                optimizer="sgd", optimizer_params={"learning_rate": 0.2},
                initializer=mx.init.Xavier(rnd_type="gaussian",
                                           factor_type="in", magnitude=2),
                eval_metric="acc")
        params, _ = mod.get_params()
        score = mod.score(it, mx.metric.Accuracy())
        return params, dict(score)["accuracy"]

    p_mp, acc_mp = train({"dev1": mx.cpu(1), "dev2": mx.cpu(2)})
    p_sd, acc_sd = train(None)
    assert acc_mp > 0.6
    assert abs(acc_mp - acc_sd) < 1e-6
    for k in p_sd:
        np.testing.assert_allclose(p_mp[k].asnumpy(), p_sd[k].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_model_parallel_lstm():
    """Reference example/model-parallel/lstm/lstm.py:65-75 pattern: each
    LSTM layer + the decoder in its own ctx group, trained end-to-end;
    numerics must match the single-device run."""
    from mxnet_tpu import rnn

    np.random.seed(0)
    T, B, D, H, C = 5, 8, 6, 12, 4
    X = np.random.randn(16, T, D).astype("float32")
    y = np.random.randint(0, C, (16,)).astype("float32")

    def build():
        data = sym.Variable("data")
        stack = rnn.SequentialRNNCell()
        for i in range(2):
            with sym.AttrScope(ctx_group="layer%d" % i):
                stack.add(rnn.LSTMCell(H, prefix="lstm%d_" % i))
        outputs, _ = stack.unroll(T, inputs=data, layout="NTC",
                                  merge_outputs=True)
        with sym.AttrScope(ctx_group="decode"):
            last = sym.SequenceLast(sym.transpose(outputs, axes=(1, 0, 2)))
            logits = sym.FullyConnected(last, num_hidden=C, name="cls")
        return sym.SoftmaxOutput(logits, sym.Variable("softmax_label"),
                                 name="softmax")

    def train(group2ctxs):
        np.random.seed(0)
        mx.random.seed(0)
        mod = mx.mod.Module(build(), context=mx.cpu(0),
                            group2ctxs=group2ctxs)
        it = mx.io.NDArrayIter(X, y, batch_size=B,
                               label_name="softmax_label")
        mod.fit(it, num_epoch=3, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                initializer=mx.init.Xavier())
        params, _ = mod.get_params()
        return params

    g2c = {"layer0": mx.cpu(1), "layer1": mx.cpu(2), "decode": mx.cpu(3)}
    p_mp = train(g2c)
    p_sd = train(None)
    for k in p_sd:
        np.testing.assert_allclose(p_mp[k].asnumpy(), p_sd[k].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_group2ctxs_with_dp_raises():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=2)
    with pytest.raises(ValueError):
        mx.mod.Module(out, context=[mx.cpu(0), mx.cpu(1)],
                      group2ctxs={"dev1": mx.cpu(2)})
