"""Module training-harness tests (modelled on tests/python/unittest/test_module.py
+ tests/python/train/test_mlp.py convergence tests)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _mlp_sym(num_hidden=32, num_classes=10):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=num_hidden)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(data=net, name="softmax")


def _mnist_iters(batch_size=100, flat=True):
    train = mx.io.MNISTIter(image="train-x", batch_size=batch_size, flat=flat)
    val = mx.io.MNISTIter(image="t10k-x", label="t10k-y", batch_size=batch_size,
                          flat=flat)
    return train, val


def test_module_fit_mlp_converges():
    # ref: tests/python/train/test_mlp.py — small end-to-end convergence
    train, val = _mnist_iters()
    mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc", num_epoch=3)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, "MLP should converge on synthetic MNIST: %s" % score


def test_module_fit_conv_converges():
    # ref: tests/python/train/test_conv.py
    train, val = _mnist_iters(flat=False)
    data = sym.Variable("data")
    net = sym.Convolution(data=data, kernel=(5, 5), num_filter=8, name="conv1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=10, name="fc")
    net = sym.SoftmaxOutput(data=net, name="softmax")
    mod = mx.mod.Module(symbol=net, context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc", num_epoch=2)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.9, "convnet should converge: %s" % score


def test_module_predict():
    train, val = _mnist_iters()
    mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=1)
    preds = mod.predict(val)
    assert preds.shape[1] == 10
    np.testing.assert_allclose(preds.asnumpy().sum(1), 1.0, rtol=1e-4)


def test_module_get_set_params():
    train, _ = _mnist_iters()
    mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.init.Uniform(0.05))
    args, auxs = mod.get_params()
    assert set(args) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    args["fc1_weight"][:] = 7.0
    mod.set_params(args, auxs)
    np.testing.assert_allclose(mod._exec.arg_dict["fc1_weight"].asnumpy(), 7.0)


def test_module_checkpoint_roundtrip(tmp_path):
    train, val = _mnist_iters()
    mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=2)
    ref = mod.score(val, "acc")[0][1]
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 2)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0002.params")
    sym2, args, auxs = mx.model.load_checkpoint(prefix, 2)
    mod2 = mx.mod.Module(symbol=sym2, context=mx.cpu())
    mod2.bind(val.provide_data, val.provide_label, for_training=False)
    mod2.set_params(args, auxs)
    assert abs(mod2.score(val, "acc")[0][1] - ref) < 1e-6


def test_module_kvstore_local_equivalent_to_none():
    def run(kvstore):
        np.random.seed(7)
        mx.random.seed(7)
        train, val = _mnist_iters()
        mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1}, kvstore=kvstore,
                num_epoch=1)
        return mod.score(val, "acc")[0][1]

    acc_kv = run("local")
    acc_none = run(None)
    assert abs(acc_kv - acc_none) < 0.02, (acc_kv, acc_none)


def test_module_fixed_params():
    train, _ = _mnist_iters()
    mod = mx.mod.Module(symbol=_mlp_sym(), context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.init.Uniform(0.05))
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    batch = next(iter(train))
    mod.forward_backward(batch)
    mod.update()
    np.testing.assert_allclose(mod._exec.arg_dict["fc1_weight"].asnumpy(), w_before)
    train.reset()


def test_optimizer_registry():
    for name in ["sgd", "adam", "rmsprop", "adagrad", "adadelta", "ftrl",
                 "nag", "signum", "adamax", "nadam", "ftml"]:
        opt = mx.optimizer.create(name)
        w = nd.array([1.0, 2.0])
        g = nd.array([0.1, -0.1])
        state = opt.create_state(0, w)
        opt.update(0, w, g, state)
        assert np.all(np.isfinite(w.asnumpy())), name


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert sched(1) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25
    multi = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1,
                                                 base_lr=1.0)
    assert multi(1) == 1.0
    assert abs(multi(10) - 0.1) < 1e-12
    assert abs(multi(20) - 0.01) < 1e-12


def test_metrics():
    m = mx.metric.create("acc")
    m.update([nd.array([1.0, 0.0])], [nd.array([[0.1, 0.9], [0.3, 0.7]])])
    assert m.get()[1] == 0.5
    mse = mx.metric.create("mse")
    mse.update([nd.array([1.0, 2.0])], [nd.array([1.5, 2.5])])
    assert abs(mse.get()[1] - 0.25) < 1e-6
    comp = mx.metric.create(["acc", "mse"])
    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update([nd.array([2.0])], [nd.array([[0.3, 0.1, 0.2]])])
    assert topk.get()[1] == 1.0


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype("float32")
    label = np.arange(10).astype("float32")
    it = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[-1].pad == 2
    it = mx.io.NDArrayIter(data, label, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 3
