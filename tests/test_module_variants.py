"""SequentialModule + PythonModule tests (models: reference
tests/python/unittest/test_module.py sequential/python module cases)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    return x, y


def test_sequential_module_fit():
    """Two chained symbol modules train end-to-end through fit."""
    net1 = mx.sym.Variable("data")
    net1 = mx.sym.FullyConnected(net1, name="fc1", num_hidden=16)
    net1 = mx.sym.Activation(net1, name="relu1", act_type="relu")

    net2 = mx.sym.Variable("fc1relu")
    net2 = mx.sym.FullyConnected(net2, name="fc2", num_hidden=2)
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

    mod1 = mx.mod.Module(net1, data_names=["data"], label_names=[],
                         context=mx.cpu())
    mod2 = mx.mod.Module(net2, data_names=["fc1relu"],
                         label_names=["softmax_label"], context=mx.cpu())
    seq = mx.mod.SequentialModule()
    seq.add(mod1).add(mod2, take_labels=True, auto_wiring=True)

    x, y = _data()
    it = mx.io.NDArrayIter(x, y, batch_size=64)
    seq.fit(it, num_epoch=20, optimizer_params={"learning_rate": 0.3})
    score = seq.score(it, mx.metric.Accuracy())
    assert score[0][1] > 0.9, score


def test_sequential_module_properties():
    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc1",
                                 num_hidden=4)
    mod1 = mx.mod.Module(net1, data_names=["data"], label_names=[],
                         context=mx.cpu())
    seq = mx.mod.SequentialModule().add(mod1)
    assert seq.data_names == ["data"]
    seq.bind(data_shapes=[("data", (2, 8))])
    assert seq.output_shapes[0][1] == (2, 4)


def test_python_loss_module_in_sequence():
    """Symbol feature module + python loss head: the reference's
    PythonLossModule workflow (python_module.py:240)."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc", num_hidden=2)
    mod = mx.mod.Module(net, data_names=["data"], label_names=[],
                        context=mx.cpu())
    loss = mx.mod.PythonLossModule()
    seq = mx.mod.SequentialModule()
    seq.add(mod).add(loss, take_labels=True, auto_wiring=True)

    x, y = _data()
    it = mx.io.NDArrayIter(x, y, batch_size=64,
                           label_name="softmax_label")
    seq.fit(it, num_epoch=10, optimizer_params={"learning_rate": 0.2})
    # predictions from the chained forward
    it.reset()
    batch = next(iter(it))
    seq.forward(batch, is_train=False)
    out = seq.get_outputs()[0].asnumpy()
    acc = (out.argmax(axis=1) == batch.label[0].asnumpy()).mean()
    assert acc > 0.9, acc


def test_python_loss_module_custom_grad():
    calls = []

    def grad_func(scores, labels):
        calls.append(1)
        s = scores.asnumpy()
        lab = labels.asnumpy().astype(int)
        e = np.exp(s - s.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        p[np.arange(len(lab)), lab] -= 1
        return p / len(lab)

    loss = mx.mod.PythonLossModule(grad_func=grad_func)
    loss.bind(data_shapes=[("data", (4, 2))],
              label_shapes=[("softmax_label", (4,))])
    loss.init_params()
    batch = mx.io.DataBatch([nd.ones((4, 2))], [nd.zeros((4,))])
    loss.forward(batch, is_train=True)
    loss.backward()
    g = loss.get_input_grads()[0].asnumpy()
    assert calls and g.shape == (4, 2)
    np.testing.assert_allclose(g.sum(), 0.0, atol=1e-6)
