"""Multi-host distribution: two jax.distributed controller processes
form one pod; collectives cross the process boundary and match
single-process numerics exactly.

ref: the reference's multi-host path is ps-lite over TCP
(src/kvstore/kvstore_dist.h:54-58, launched by tools/launch.py ssh/mpi
trackers); ours is jax.distributed + XLA collectives (mxnet_tpu/dist.py)
launched by tools/launch.py --launcher jax.  CPU + gloo stands in for
DCN in this environment."""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import launch  # noqa: E402

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def test_two_process_pod_matches_single_process(tmp_path):
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # one device per process: the pod has exactly 2 devices
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    codes = launch.launch_jax(
        2, [sys.executable, _WORKER, str(tmp_path)], env=env)
    assert codes == [0, 0], codes
    ws = []
    for r in (0, 1):
        with open(tmp_path / ("rank%d.json" % r)) as f:
            ws.append(json.load(f)["w"])
    # both controllers observe the identical updated replica
    np.testing.assert_array_equal(ws[0], ws[1])


def test_dist_module_env_contract(monkeypatch):
    from mxnet_tpu import dist

    monkeypatch.delenv("MXNET_COORDINATOR_ADDRESS", raising=False)
    assert dist.env_spec() is None
    assert dist.initialize() in (False, True)  # no env: no-op probe
    monkeypatch.setenv("MXNET_COORDINATOR_ADDRESS", "10.0.0.1:9123")
    monkeypatch.setenv("MXNET_NUM_PROCESSES", "16")
    monkeypatch.setenv("MXNET_PROCESS_ID", "3")
    assert dist.env_spec() == ("10.0.0.1:9123", 16, 3)
    with pytest.raises(ValueError):
        dist.initialize(coordinator_address="x:1")


@pytest.mark.slow
def test_four_process_pod_two_devices_each(tmp_path):
    """Beyond-minimum pod: 4 processes x 2 virtual devices = 8-device
    mesh; dist_sync identity from jax.distributed (no DMLC env);
    row_sparse gradient exchange across the pod (VERDICT r2 item 8)."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    for k in ("DMLC_PS_ROOT_URI", "DMLC_ROLE", "DMLC_NUM_SERVER",
              "DMLC_NUM_WORKER"):
        env[k] = ""  # force the jax.distributed identity path
    codes = launch.launch_jax(
        4, [sys.executable,
            os.path.join(os.path.dirname(__file__),
                         "multihost_worker4.py"), str(tmp_path)], env=env)
    assert codes == [0, 0, 0, 0], codes
    ws = []
    for r in range(4):
        with open(tmp_path / ("rank%d.json" % r)) as f:
            ws.append(json.load(f)["w"])
    for r in range(1, 4):
        np.testing.assert_array_equal(ws[0], ws[r])
