"""NDArray semantics tests (modelled on tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation_and_basic_props():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert a.size == 6
    assert a.ndim == 2
    b = nd.ones((2, 3), dtype="float64")
    assert b.dtype == np.float64
    c = nd.array([[1, 2], [3, 4]])
    np.testing.assert_array_equal(c.asnumpy(), [[1, 2], [3, 4]])
    d = nd.full((2, 2), 7.5)
    np.testing.assert_allclose(d.asnumpy(), 7.5)
    e = nd.arange(0, 10, 2)
    np.testing.assert_array_equal(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((1 + a).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((2 - a).asnumpy(), [[1, 0], [-1, -2]])
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    np.testing.assert_allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])


def test_inplace_mutation():
    a = nd.ones((2, 2))
    orig = a
    a += 5
    assert a is orig  # cell identity preserved — the ThreadedVar contract
    np.testing.assert_allclose(a.asnumpy(), 6.0)
    a *= 2
    np.testing.assert_allclose(a.asnumpy(), 12.0)


def test_setitem_getitem():
    a = nd.zeros((3, 4))
    a[1] = 1.0
    np.testing.assert_allclose(a.asnumpy()[1], 1.0)
    a[0, 2] = 5.0
    assert a.asnumpy()[0, 2] == 5.0
    a[2, 1:3] = nd.array([7.0, 8.0])
    np.testing.assert_allclose(a.asnumpy()[2, 1:3], [7, 8])
    sub = a[1]
    assert sub.shape == (4,)
    sub2 = a[0:2]
    assert sub2.shape == (2, 4)


def test_reshape_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)
    assert a.reshape(6, 4).shape == (6, 4)


def test_reductions():
    a = nd.array(np.arange(24).reshape(2, 3, 4).astype("float32"))
    np.testing.assert_allclose(a.sum().asnumpy(), np.arange(24).sum())
    np.testing.assert_allclose(
        a.sum(axis=1).asnumpy(), np.arange(24).reshape(2, 3, 4).sum(axis=1)
    )
    np.testing.assert_allclose(a.mean().asnumpy(), np.arange(24).mean())
    np.testing.assert_allclose(a.max(axis=(0, 2)).asnumpy(),
                               np.arange(24).reshape(2, 3, 4).max(axis=(0, 2)))
    np.testing.assert_allclose(
        nd.sum(a, axis=1, keepdims=True).asnumpy(),
        np.arange(24).reshape(2, 3, 4).sum(axis=1, keepdims=True),
    )


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype("float32"))
    b = nd.array(np.random.rand(4, 5).astype("float32"))
    np.testing.assert_allclose(
        nd.dot(a, b).asnumpy(), a.asnumpy() @ b.asnumpy(), rtol=1e-5
    )
    np.testing.assert_allclose(
        nd.dot(a, b, transpose_a=False, transpose_b=False).asnumpy(),
        a.asnumpy() @ b.asnumpy(),
        rtol=1e-5,
    )
    c = nd.array(np.random.rand(2, 3, 4).astype("float32"))
    d = nd.array(np.random.rand(2, 4, 5).astype("float32"))
    np.testing.assert_allclose(
        nd.batch_dot(c, d).asnumpy(), c.asnumpy() @ d.asnumpy(), rtol=1e-5
    )


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2
    np.testing.assert_allclose(parts[0].asnumpy(), 1.0)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_cast_astype():
    a = nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.float16
    c = a.astype(np.int32)
    assert c.dtype == np.int32


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.params")
    d = {"w": nd.array([[1.0, 2.0]]), "b": nd.array([3.0])}
    nd.save(fname, d)
    loaded = nd.load(fname)
    np.testing.assert_allclose(loaded["w"].asnumpy(), [[1, 2]])
    np.testing.assert_allclose(loaded["b"].asnumpy(), [3])
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2


def test_copyto_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    b = a.copyto(mx.cpu(0))
    np.testing.assert_allclose(b.asnumpy(), 1.0)
    c = a.as_in_context(mx.cpu(0))
    assert c is a


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(a, k=2)
    np.testing.assert_array_equal(idx.asnumpy(), [[0, 2], [1, 2]])
    vals = nd.topk(a, k=2, ret_typ="value")
    np.testing.assert_allclose(vals.asnumpy(), [[3, 2], [5, 4]])
    s = nd.sort(a, axis=-1)
    np.testing.assert_allclose(s.asnumpy(), [[1, 2, 3], [0, 4, 5]])


def test_random_ops():
    mx.random.seed(42)
    a = nd.random.uniform(0, 1, shape=(100,))
    assert a.shape == (100,)
    assert 0 <= float(a.min().asscalar()) and float(a.max().asscalar()) <= 1
    b1 = nd.random.normal(0, 1, shape=(50,))
    mx.random.seed(42)
    a2 = nd.random.uniform(0, 1, shape=(100,))
    np.testing.assert_allclose(a.asnumpy(), a2.asnumpy())  # determinism


def test_broadcast_ops():
    a = nd.array([[1.0], [2.0]])
    b = nd.array([[10.0, 20.0]])
    np.testing.assert_allclose(nd.broadcast_add(a, b).asnumpy(), [[11, 21], [12, 22]])
    c = nd.broadcast_to(nd.array([[1.0, 2.0]]), shape=(3, 2))
    assert c.shape == (3, 2)


def test_embedding_take_onehot():
    w = nd.array(np.arange(12).reshape(4, 3).astype("float32"))
    idx = nd.array([0, 2])
    out = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    np.testing.assert_allclose(out.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    t = nd.take(w, idx, axis=0)
    np.testing.assert_allclose(t.asnumpy(), out.asnumpy())
    oh = nd.one_hot(nd.array([1, 3]), depth=4)
    np.testing.assert_allclose(oh.asnumpy(), [[0, 1, 0, 0], [0, 0, 0, 1]])


def test_wait_and_scalar():
    a = nd.ones((1,))
    a.wait_to_read()
    assert a.asscalar() == 1.0
    nd.waitall()
