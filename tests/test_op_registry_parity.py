"""Registry-diff against the reference's registration sites (VERDICT r4
missing #5: `cast_storage`/`_sparse_retain` existed as functions but not
as creators, and nothing pinned the diff, so the hole went unseen for
two rounds).

The scan walks every NNVM_REGISTER_OP / MXNET_REGISTER_OP_PROPERTY /
MXNET_OPERATOR_REGISTER_* call in /root/reference/src/operator and
/root/reference/plugin (the same macro families
src/operator/operator.cc + nnvm expand into registry entries) and
asserts every public name resolves in mxnet_tpu's creator registry
(aliases count — the C ABI resolves creators through the same
list_ops(include_aliases=True) surface, native/c_api.cc:381).
"""
import glob
import os
import re

import pytest

REF_OP_DIRS = ["/root/reference/src/operator", "/root/reference/plugin"]

_MACRO = re.compile(
    r"(?:NNVM_REGISTER_OP|MXNET_REGISTER_OP_PROPERTY|"
    r"MXNET_OPERATOR_REGISTER_[A-Z_0-9]+)\(\s*([A-Za-z0-9_]+)")


def _strip_macro_definitions(src):
    """Drop #define blocks (including backslash continuations): macro
    DEFINITIONS register nothing — only call sites count."""
    out = []
    in_define = False
    for ln in src.splitlines():
        if in_define:
            in_define = ln.rstrip().endswith("\\")
            continue
        if ln.lstrip().startswith("#define"):
            in_define = ln.rstrip().endswith("\\")
            continue
        out.append(ln)
    return "\n".join(out)


def _reference_registrations():
    names = {}
    for d in REF_OP_DIRS:
        for f in glob.glob(os.path.join(d, "**", "*.cc"), recursive=True):
            src = open(f, encoding="utf-8", errors="replace").read()
            body = _strip_macro_definitions(src)
            for m in re.finditer(
                    r"(NNVM_REGISTER_OP|MXNET_REGISTER_OP_PROPERTY|"
                    r"MXNET_OPERATOR_REGISTER_[A-Z_0-9]+)"
                    r"\(\s*([A-Za-z0-9_]+)", body):
                macro, arg = m.groups()
                # token-pasting families: the registered name is the
                # macro's expansion, not its first argument
                # (multisample_op.cc:37 NNVM_REGISTER_OP(_sample_##distr))
                if macro.startswith("MXNET_OPERATOR_REGISTER_SAMPLING"):
                    arg = "_sample_" + arg
                names.setdefault(arg, f)
            # TIsBackward-marked ops are gradient nodes the functional
            # substrate never materializes by name
            for m in re.finditer(
                    r'NNVM_REGISTER_OP\(\s*([A-Za-z0-9_]+)\s*\)'
                    r'[^;]*?TIsBackward',
                    src, re.S):
                names.pop(m.group(1), None)
    return names


def _public(names):
    return {n: f for n, f in names.items()
            if not n.startswith("_backward")}


def test_every_reference_creator_resolves():
    if not any(os.path.isdir(d) for d in REF_OP_DIRS):
        pytest.skip("reference source tree not present on this box")
    pytest.importorskip("jax")
    import mxnet_tpu  # noqa: F401  (triggers every registration)
    from mxnet_tpu.ops import registry

    ref = _public(_reference_registrations())
    assert len(ref) > 200, "scan broke: only %d reference ops found" \
        % len(ref)
    have = set(registry.list_ops(include_aliases=True))
    missing = sorted(n for n in ref if n not in have)
    assert not missing, (
        "reference-registered creators missing from the registry: %s "
        "(registered at e.g. %s)"
        % (missing, {n: ref[n] for n in missing[:5]}))


def test_regression_creators_of_round4():
    """The two named misses of VERDICT r4 stay fixed, at both the
    python-symbol and registry surfaces."""
    import mxnet_tpu as mx
    from mxnet_tpu.ops import registry

    assert registry.exists("cast_storage")
    assert registry.exists("_sparse_retain")
    s = mx.sym.cast_storage(mx.sym.Variable("d"), stype="row_sparse")
    assert s.list_arguments() == ["d"]


def test_legacy_native_creator_materializes_label_input():
    """NumpyOp.get_symbol composes through the _Native creator; the
    prop's unfed inputs (label) must auto-create as variables and
    infer through prop.infer_shape — the reference legacy contract
    (python/mxnet/operator.py:144 NumpyOp; regression: round-5's first
    creator wiring dropped the label, verified by review)."""
    import numpy as np

    import mxnet_tpu as mx

    class Softmax(mx.operator.NumpyOp):
        def __init__(self):
            super().__init__(False)

        def list_arguments(self):
            return ["data", "label"]

        def infer_shape(self, in_shape):
            return [in_shape[0], (in_shape[0][0],)], [in_shape[0]]

        def forward(self, in_data, out_data):
            x, y = in_data[0], out_data[0]
            y[:] = np.exp(x - x.max(axis=1, keepdims=True))
            y /= y.sum(axis=1, keepdims=True)

        def backward(self, out_grad, in_data, out_data, in_grad):
            lab, y, dx = in_data[1], out_data[0], in_grad[0]
            dx[:] = y
            dx[np.arange(lab.shape[0]), lab.astype(np.int32)] -= 1.0

    net = Softmax()(data=mx.sym.Variable("data"), name="softmax")
    assert net.list_arguments() == ["data", "softmax_label"]
    arg_shapes, out_shapes, _ = net.infer_shape(data=(8, 5))
    assert arg_shapes[1] == (8,)
    assert out_shapes[0] == (8, 5)
    ex = net.bind(
        mx.cpu(),
        {"data": mx.nd.array(np.random.rand(8, 5).astype("float32")),
         "softmax_label": mx.nd.array(np.arange(8.0) % 5)},
        args_grad={"data": mx.nd.zeros((8, 5))},
        grad_req={"data": "write", "softmax_label": "null"})
    out = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    ex.backward()
    # softmax-minus-onehot gradient sums to ~0 per row
    g = ex.grad_dict["data"].asnumpy()
    np.testing.assert_allclose(g.sum(), 0.0, atol=1e-5)


def test_sparse_retain_creator_matches_imperative():
    """Dense lowering of _sparse_retain == the imperative
    RowSparse.retain image."""
    import numpy as np

    import mxnet_tpu as mx

    dense = np.arange(20, dtype="float32").reshape(5, 4)
    keep = np.array([0, 3], dtype="float32")
    sym = mx.sym.sparse.retain(mx.sym.Variable("data"),
                               mx.sym.Variable("indices"))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array(dense),
                             "indices": mx.nd.array(keep)})
    out = ex.forward()[0].asnumpy()
    rsp = mx.nd.array(dense).tostype("row_sparse")
    expect = rsp.retain(mx.nd.array(keep)).tostype("default").asnumpy()
    np.testing.assert_allclose(out, expect)
