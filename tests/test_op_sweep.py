"""Systematic operator sweep — dense parameterization in the style of
the reference's tests/python/unittest/test_operator.py: every op family
exercised over edge shapes and dtypes, with finite-difference gradient
checks for the differentiable ones and golden-numpy forward checks.

The sweep is table-driven so adding an op is one line.  Shapes include
the awkward cases the reference parameterizes: singleton dims, length-1
axes, non-square, odd sizes (TPU lane-unaligned on purpose).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import check_numeric_gradient

def _rng_for(*key):
    """Deterministic per-(test,shape) RNG: results depend on neither
    test execution order nor the process hash seed."""
    import zlib

    return np.random.RandomState(zlib.crc32(repr(key).encode()))


RNG = np.random.RandomState(77)

SHAPES = [(1,), (7,), (2, 3), (1, 5), (3, 1), (2, 3, 4), (1, 1, 1),
          (2, 1, 3, 2)]

# (op name, extra kwargs, domain) — unary elementwise, differentiable
UNARY = [
    ("sigmoid", {}, (-4, 4)), ("tanh", {}, (-3, 3)),
    ("relu", {}, (-2, 2)), ("softsign", {}, (-3, 3)),
    ("exp", {}, (-2, 2)), ("log", {}, (0.2, 4)),
    ("log2", {}, (0.2, 4)), ("log10", {}, (0.2, 4)),
    ("log1p", {}, (-0.5, 3)), ("expm1", {}, (-2, 2)),
    ("sqrt", {}, (0.2, 5)), ("cbrt", {}, (0.2, 5)),
    ("rsqrt", {}, (0.3, 5)), ("square", {}, (-3, 3)),
    ("sin", {}, (-3, 3)), ("cos", {}, (-3, 3)),
    ("tan", {}, (-1, 1)), ("arcsin", {}, (-0.9, 0.9)),
    ("arccos", {}, (-0.9, 0.9)), ("arctan", {}, (-3, 3)),
    ("sinh", {}, (-2, 2)), ("cosh", {}, (-2, 2)),
    ("arcsinh", {}, (-3, 3)), ("arctanh", {}, (-0.9, 0.9)),
    ("erf", {}, (-2, 2)), ("gamma", {}, (0.5, 4)),
    ("gammaln", {}, (0.5, 4)), ("hard_sigmoid", {}, (-1.5, 1.5)),
    ("softmax", {"axis": -1}, (-2, 2)),
    ("log_softmax", {"axis": -1}, (-2, 2)),
]

# binary broadcasting ops
BINARY = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "broadcast_hypot",
]

REDUCE = [
    ("sum", {}), ("mean", {}), ("prod", {}),
    ("sum", {"axis": 0}), ("mean", {"axis": -1, "keepdims": True}),
    ("nansum", {}), ("norm", {}),
]


def _rand(shape, lo, hi, rng=None, dtype=np.float64):
    """float64 by default: finite-difference gradient checks need the
    headroom (f32 truncation noise at eps=1e-4 swamps small grads);
    forward-only checks cast down where dtype matters."""
    rng = rng if rng is not None else RNG
    return nd.array(rng.uniform(lo, hi, shape).astype(dtype))


@pytest.mark.parametrize("op,kw,dom", UNARY,
                         ids=[u[0] + str(i) for i, u in enumerate(UNARY)])
def test_unary_forward_and_grad(op, kw, dom):
    import scipy.special  # noqa: F401  (only for the few special fns)

    fn = getattr(nd, op)
    for shape in (SHAPES[1], SHAPES[3], SHAPES[5]):
        x = _rand(shape, *dom, rng=_rng_for(op, shape))
        # forward matches numpy/scipy reference where one exists
        y = fn(x, **kw).asnumpy()
        assert y.shape == np.broadcast_shapes(y.shape, x.shape)
        assert np.isfinite(y).all(), (op, shape)
        check_numeric_gradient(lambda a: fn(a, **kw), [x], rtol=5e-2,
                               atol=5e-3)


@pytest.mark.parametrize("op", BINARY)
def test_binary_broadcast_grad(op):
    fn = getattr(nd, op)
    cases = [((2, 3), (2, 3)), ((2, 3), (1, 3)), ((4, 1), (1, 5)),
             ((1,), (3, 2)), ((2, 1, 2), (1, 3, 1))]
    for sa, sb in cases:
        lo, hi = (0.5, 2.0) if op in ("broadcast_power",
                                      "broadcast_div",
                                      "broadcast_hypot") else (-2.0, 2.0)
        a, b = _rand(sa, lo, hi), _rand(sb, lo, hi)
        out = fn(a, b)
        want = np.broadcast_shapes(sa, sb)
        assert out.shape == want, (op, sa, sb)
        if op in ("broadcast_maximum", "broadcast_minimum"):
            continue  # kink at ties: finite differences are undefined
        check_numeric_gradient(lambda x, y: fn(x, y), [a, b], rtol=5e-2,
                               atol=5e-3)


@pytest.mark.parametrize("op,kw", REDUCE,
                         ids=["%s-%d" % (r[0], i)
                              for i, r in enumerate(REDUCE)])
def test_reduce_forward_and_grad(op, kw):
    fn = getattr(nd, op)
    for shape in ((3, 4), (2, 1, 3), (5,)):
        x = _rand(shape, 0.5, 2.0)
        got = fn(x, **kw).asnumpy()
        ref = {"sum": np.sum, "mean": np.mean, "prod": np.prod,
               "nansum": np.nansum,
               "norm": np.linalg.norm}[op]
        kwargs = {k: v for k, v in kw.items() if k in ("axis", "keepdims")}
        if op == "norm":
            want = np.asarray(ref(x.asnumpy().ravel()))
        else:
            want = np.asarray(ref(x.asnumpy(), **kwargs))
        np.testing.assert_allclose(got.reshape(want.shape), want,
                                   rtol=1e-5)
        check_numeric_gradient(lambda a: fn(a, **kw), [x], rtol=5e-2,
                               atol=5e-3)


@pytest.mark.parametrize("dtype", ["float32", "float64", "float16",
                                   "int32", "int64", "uint8"])
def test_dtype_arith_and_cast(dtype):
    x = nd.array(np.arange(1, 7).reshape(2, 3), dtype=dtype)
    y = (x + x).asnumpy()
    assert y.dtype == np.dtype(dtype)
    np.testing.assert_allclose(y.astype(np.float64),
                               2.0 * np.arange(1, 7).reshape(2, 3))
    for to in ("float32", "int32"):
        z = x.astype(to)
        assert str(z.dtype).endswith(to)


@pytest.mark.parametrize("axis", [0, 1, -1])
def test_concat_split_roundtrip(axis):
    parts = [nd.array(RNG.randn(2, 3, 2).astype(np.float32))
             for _ in range(3)]
    cat = nd.concat(*parts, dim=axis)
    back = nd.split(cat, num_outputs=3, axis=axis)
    for p, b in zip(parts, back):
        np.testing.assert_allclose(p.asnumpy(), b.asnumpy())
    check_numeric_gradient(
        lambda a, b, c: nd.concat(a, b, c, dim=axis), parts,
        rtol=5e-2, atol=5e-3)


def test_conv_pool_grads_edge_shapes():
    """Convolution/Pooling at the awkward shapes the reference
    parameterizes: kernel == input, stride > kernel, channels 1."""
    cases = [
        # (in_shape, kernel, stride, pad, num_filter)
        ((1, 1, 5, 5), (3, 3), (1, 1), (0, 0), 2),
        ((2, 3, 4, 4), (4, 4), (1, 1), (0, 0), 1),   # kernel == input
        ((1, 2, 7, 5), (3, 3), (3, 3), (1, 1), 4),   # stride > 1, pad
        ((2, 1, 6, 6), (1, 1), (2, 2), (0, 0), 3),   # 1x1 kernel
    ]
    for in_shape, k, s, p, nf in cases:
        x = _rand(in_shape, -1, 1)
        w = _rand((nf, in_shape[1]) + k, -0.5, 0.5)
        b = _rand((nf,), -0.1, 0.1)
        out = nd.Convolution(x, w, b, kernel=k, stride=s, pad=p,
                             num_filter=nf)
        assert out.shape[0] == in_shape[0] and out.shape[1] == nf
        check_numeric_gradient(
            lambda a, ww, bb: nd.Convolution(
                a, ww, bb, kernel=k, stride=s, pad=p, num_filter=nf),
            [x, w, b], rtol=5e-2, atol=5e-3)
    for pool_type in ("max", "avg"):
        x = _rand((2, 2, 5, 5), -1, 1)
        out = nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                         pool_type=pool_type)
        assert out.shape == (2, 2, 2, 2)
        if pool_type == "avg":  # max pool grad is kinked at ties
            check_numeric_gradient(
                lambda a: nd.Pooling(a, kernel=(2, 2), stride=(2, 2),
                                     pool_type="avg"),
                [x], rtol=5e-2, atol=5e-3)


def test_fullyconnected_flatten_modes_grad():
    x = _rand((3, 2, 4), -1, 1)
    w = _rand((6, 8), -0.5, 0.5)
    b = _rand((6,), -0.1, 0.1)
    out = nd.FullyConnected(x, w, b, num_hidden=6)
    assert out.shape == (3, 6)
    check_numeric_gradient(
        lambda a, ww, bb: nd.FullyConnected(a, ww, bb, num_hidden=6),
        [x, w, b], rtol=5e-2, atol=5e-3)
    w2 = _rand((6, 4), -0.5, 0.5)
    out2 = nd.FullyConnected(x, w2, b, num_hidden=6, flatten=False)
    assert out2.shape == (3, 2, 6)


def test_batchnorm_modes_grad():
    x = _rand((4, 3, 2, 2), -2, 2)
    gamma = nd.ones((3,))
    beta = nd.zeros((3,))
    mm = nd.zeros((3,))
    mv = nd.ones((3,))
    with autograd.train_mode():
        out = nd.BatchNorm(x, gamma, beta, mm, mv, fix_gamma=False)
    assert out.shape == x.shape
    m = out.asnumpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0.0, atol=1e-5)
    # inference mode uses the running stats (mean 0, var 1 => the only
    # effect is the 1/sqrt(1+eps) scale, default eps=1e-3)
    out_inf = nd.BatchNorm(x, gamma, beta, nd.zeros((3,)), nd.ones((3,)),
                           fix_gamma=False, use_global_stats=True)
    np.testing.assert_allclose(out_inf.asnumpy(),
                               x.asnumpy() / np.sqrt(1.0 + 1e-3),
                               atol=1e-5)


def test_transpose_slice_reverse_grads():
    x = _rand((2, 3, 4), -2, 2)
    np.testing.assert_allclose(
        nd.transpose(x, axes=(2, 0, 1)).asnumpy(),
        np.transpose(x.asnumpy(), (2, 0, 1)))
    check_numeric_gradient(lambda a: nd.transpose(a, axes=(2, 0, 1)),
                           [x], rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(
        nd.slice(x, begin=(0, 1, 1), end=(2, 3, 3)).asnumpy(),
        x.asnumpy()[0:2, 1:3, 1:3])
    check_numeric_gradient(
        lambda a: nd.slice(a, begin=(0, 1, 1), end=(2, 3, 3)), [x],
        rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(
        nd.reverse(x, axis=1).asnumpy(), x.asnumpy()[:, ::-1, :])


def test_take_gather_scatter_grads():
    x = _rand((5, 3), -2, 2)
    idx = nd.array(np.array([0, 4, 2, 2], np.float32))
    out = nd.take(x, idx)
    np.testing.assert_allclose(
        out.asnumpy(), x.asnumpy()[[0, 4, 2, 2]])
    check_numeric_gradient(lambda a: nd.take(a, idx), [x], rtol=5e-2,
                           atol=5e-3)
    oh = nd.one_hot(idx, depth=5).asnumpy()
    assert oh.shape == (4, 5) and oh.sum() == 4


def test_where_clip_grads():
    c = nd.array((RNG.rand(3, 4) > 0.5).astype(np.float32))
    a, b = _rand((3, 4), -2, 2), _rand((3, 4), -2, 2)
    np.testing.assert_allclose(
        nd.where(c, a, b).asnumpy(),
        np.where(c.asnumpy() > 0, a.asnumpy(), b.asnumpy()))
    x = _rand((6,), -3, 3)
    np.testing.assert_allclose(
        nd.clip(x, -1, 1).asnumpy(), np.clip(x.asnumpy(), -1, 1))


def test_dot_batch_dot_transpose_flags_grad():
    a = _rand((3, 4), -1, 1)
    b = _rand((4, 5), -1, 1)
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(a, b.T, transpose_b=True).asnumpy()
        if False else nd.dot(a, b).asnumpy(),
        a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    check_numeric_gradient(lambda x, y: nd.dot(x, y), [a, b],
                           rtol=5e-2, atol=5e-3)
    ba = _rand((2, 3, 4), -1, 1)
    bb = _rand((2, 4, 2), -1, 1)
    np.testing.assert_allclose(
        nd.batch_dot(ba, bb).asnumpy(),
        np.einsum("bij,bjk->bik", ba.asnumpy(), bb.asnumpy()),
        rtol=1e-5)
