"""Operator tests (modelled on tests/python/unittest/test_operator.py —
forward numerics against numpy + finite-difference gradients)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_fully_connected_forward():
    x = np.random.rand(4, 10).astype("float32")
    w = np.random.rand(3, 10).astype("float32")
    b = np.random.rand(3).astype("float32")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=3)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-5, atol=1e-5)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w), num_hidden=3, no_bias=True)
    assert_almost_equal(out2, x @ w.T, rtol=1e-5, atol=1e-5)


def test_fully_connected_grad():
    x = nd.array(np.random.rand(3, 4).astype("float64"))
    w = nd.array(np.random.rand(2, 4).astype("float64"))
    b = nd.array(np.random.rand(2).astype("float64"))
    check_numeric_gradient(
        lambda a, ww, bb: nd.FullyConnected(a, ww, bb, num_hidden=2), [x, w, b]
    )


def test_convolution_forward_shape():
    x = nd.array(np.random.rand(2, 3, 8, 8).astype("float32"))
    w = nd.array(np.random.rand(4, 3, 3, 3).astype("float32"))
    b = nd.array(np.zeros(4, dtype="float32"))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert out.shape == (2, 4, 6, 6)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, pad=(1, 1))
    assert out.shape == (2, 4, 8, 8)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, stride=(2, 2), pad=(1, 1))
    assert out.shape == (2, 4, 4, 4)


def test_convolution_vs_numpy():
    # 1x1 conv == per-pixel matmul
    x = np.random.rand(2, 3, 5, 5).astype("float32")
    w = np.random.rand(4, 3, 1, 1).astype("float32")
    out = nd.Convolution(nd.array(x), nd.array(w), kernel=(1, 1), num_filter=4,
                         no_bias=True)
    expect = np.einsum("nchw,fc->nfhw", x, w[:, :, 0, 0])
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)


def test_convolution_grad():
    x = nd.array(np.random.rand(1, 2, 5, 5).astype("float64"))
    w = nd.array(np.random.rand(2, 2, 3, 3).astype("float64"))
    check_numeric_gradient(
        lambda a, ww: nd.Convolution(a, ww, kernel=(3, 3), num_filter=2, no_bias=True),
        [x, w],
        eps=1e-5,
    )


def test_grouped_convolution():
    x = np.random.rand(1, 4, 6, 6).astype("float32")
    w = np.random.rand(4, 2, 3, 3).astype("float32")
    out = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3), num_filter=4,
                         num_group=2, no_bias=True)
    assert out.shape == (1, 4, 4, 4)


def test_pooling():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert_almost_equal(out, [[[[5, 7], [13, 15]]]])
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert_almost_equal(out, [[[[2.5, 4.5], [10.5, 12.5]]]])
    out = nd.Pooling(nd.array(x), global_pool=True, pool_type="max", kernel=(1, 1))
    assert_almost_equal(out, [[[[15]]]])
    # full convention rounds up output size (ref: pooling_convention="full")
    x2 = nd.array(np.random.rand(1, 1, 5, 5).astype("float32"))
    out_valid = nd.Pooling(x2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    out_full = nd.Pooling(x2, kernel=(2, 2), stride=(2, 2), pool_type="max",
                          pooling_convention="full")
    assert out_valid.shape == (1, 1, 2, 2)
    assert out_full.shape == (1, 1, 3, 3)


def test_activation():
    x = np.array([-2.0, -0.5, 0.0, 1.0], dtype="float32")
    assert_almost_equal(nd.Activation(nd.array(x), act_type="relu"),
                        np.maximum(x, 0))
    assert_almost_equal(nd.Activation(nd.array(x), act_type="sigmoid"),
                        1 / (1 + np.exp(-x)), rtol=1e-5)
    assert_almost_equal(nd.Activation(nd.array(x), act_type="tanh"),
                        np.tanh(x), rtol=1e-5)
    assert_almost_equal(nd.Activation(nd.array(x), act_type="softrelu"),
                        np.log1p(np.exp(x)), rtol=1e-5)


def test_leaky_relu():
    x = np.array([-2.0, 1.0], dtype="float32")
    assert_almost_equal(nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1),
                        [-0.2, 1.0], rtol=1e-5)
    assert_almost_equal(
        nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0),
        [np.exp(-2) - 1, 1.0],
        rtol=1e-5,
    )


def test_batchnorm_train_and_inference():
    np.random.seed(0)
    x = np.random.rand(4, 3, 2, 2).astype("float32") * 5
    gamma = np.ones(3, dtype="float32")
    beta = np.zeros(3, dtype="float32")
    mm = nd.zeros(3)
    mv = nd.ones(3)
    with autograd.record():  # training mode
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta), mm, mv,
                           fix_gamma=False, momentum=0.9)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    expect = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-3)
    assert_almost_equal(out, expect, rtol=1e-3, atol=1e-4)
    # moving stats were updated in place (aux mutation contract)
    assert_almost_equal(mm, 0.1 * mean, rtol=1e-4, atol=1e-5)
    assert_almost_equal(mv, 0.9 + 0.1 * var, rtol=1e-4, atol=1e-5)
    # inference mode uses moving stats
    out_inf = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta), mm, mv,
                           fix_gamma=False)
    expect_inf = (x - mm.asnumpy()[None, :, None, None]) / np.sqrt(
        mv.asnumpy()[None, :, None, None] + 1e-3
    )
    assert_almost_equal(out_inf, expect_inf, rtol=1e-3, atol=1e-4)


def test_batchnorm_grad():
    x = nd.array(np.random.rand(3, 2, 2, 2).astype("float64"))
    gamma = nd.array(np.random.rand(2).astype("float64") + 0.5)
    beta = nd.array(np.random.rand(2).astype("float64"))
    mm = nd.zeros(2, dtype="float64")
    mv = nd.ones(2, dtype="float64")

    def f(a, g, b):
        return nd.BatchNorm(a, g, b, mm, mv, fix_gamma=False, _training=True)

    check_numeric_gradient(f, [x, gamma, beta], eps=1e-5, rtol=2e-2, atol=2e-3)


def test_softmax():
    x = np.random.rand(3, 4).astype("float32")
    out = nd.softmax(nd.array(x))
    e = np.exp(x - x.max(1, keepdims=True))
    assert_almost_equal(out, e / e.sum(1, keepdims=True), rtol=1e-5)
    lo = nd.log_softmax(nd.array(x))
    assert_almost_equal(lo, np.log(e / e.sum(1, keepdims=True)), rtol=1e-5)


def test_dropout():
    x = nd.ones((100, 100))
    # inference: identity
    out = nd.Dropout(x, p=0.5)
    assert_almost_equal(out, 1.0)
    # training: roughly half zeroed, scaled by 2
    with autograd.record():
        out = nd.Dropout(x, p=0.5)
    arr = out.asnumpy()
    frac = (arr == 0).mean()
    assert 0.4 < frac < 0.6
    nz = arr[arr != 0]
    assert_almost_equal(nz, 2.0)
    # mode=always drops at inference too
    out = nd.Dropout(x, p=0.5, mode="always")
    assert (out.asnumpy() == 0).mean() > 0.3


def test_transpose_swapaxes_etc():
    x = np.random.rand(2, 3, 4).astype("float32")
    assert nd.transpose(nd.array(x)).shape == (4, 3, 2)
    assert nd.transpose(nd.array(x), axes=(0, 2, 1)).shape == (2, 4, 3)
    assert nd.SwapAxis(nd.array(x), dim1=0, dim2=2).shape == (4, 3, 2)
    assert nd.expand_dims(nd.array(x), axis=1).shape == (2, 1, 3, 4)
    assert_almost_equal(nd.reverse(nd.array(x), axis=0), x[::-1])


def test_slice_ops():
    x = np.arange(24, dtype="float32").reshape(4, 6)
    out = nd.slice(nd.array(x), begin=(1, 2), end=(3, 5))
    assert_almost_equal(out, x[1:3, 2:5])
    out = nd.slice_axis(nd.array(x), axis=1, begin=1, end=4)
    assert_almost_equal(out, x[:, 1:4])


def test_elemwise_math():
    x = np.random.rand(5).astype("float32") + 0.5
    for name, ref in [
        ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
        ("square", np.square), ("abs", np.abs), ("sin", np.sin),
        ("cos", np.cos), ("tanh", np.tanh), ("floor", np.floor),
        ("ceil", np.ceil), ("sign", np.sign), ("log1p", np.log1p),
        ("expm1", np.expm1), ("rsqrt", lambda v: 1 / np.sqrt(v)),
    ]:
        out = getattr(nd, name)(nd.array(x))
        assert_almost_equal(out, ref(x), rtol=1e-4, atol=1e-5)


def test_where():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    assert_almost_equal(nd.where(cond, x, y), [1, 20, 3])


def test_sequence_ops():
    data = np.arange(24, dtype="float32").reshape(3, 2, 4)  # (T, N, C)
    seq_len = nd.array([2.0, 3.0])
    out = nd.SequenceMask(nd.array(data), seq_len, use_sequence_length=True, value=-1.0)
    arr = out.asnumpy()
    assert (arr[2, 0] == -1).all()
    assert (arr[2, 1] == data[2, 1]).all()
    last = nd.SequenceLast(nd.array(data), seq_len, use_sequence_length=True)
    assert_almost_equal(last, np.stack([data[1, 0], data[2, 1]]))
    rev = nd.SequenceReverse(nd.array(data), seq_len, use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], data[1, 0])
    assert_almost_equal(rev.asnumpy()[0, 1], data[2, 1])


def test_optimizer_ops():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.1, 0.2])
    out = nd.sgd_update(w, g, lr=0.1)
    assert_almost_equal(out, [0.99, 1.98], rtol=1e-5)
    mom = nd.zeros(2)
    out = nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9)
    assert_almost_equal(out, [0.99, 1.98], rtol=1e-5)
    assert_almost_equal(mom, [-0.01, -0.02], rtol=1e-5)  # state mutated in place
    mean, var = nd.zeros(2), nd.zeros(2)
    out = nd.adam_update(w, g, mean, var, lr=0.01)
    assert out.shape == (2,)
    assert float(mean.asnumpy()[0]) != 0.0


def test_norm_ops():
    x = np.random.rand(2, 3, 4).astype("float32")
    mean = x.mean(-1, keepdims=True)
    std = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    # LayerNorm normalises over last axis; gamma indexed along that axis
    expect = (x - mean) / std * np.ones(4) + 0.0
    out2 = nd.LayerNorm(nd.array(x), nd.array(np.ones(4, "float32")),
                        nd.array(np.zeros(4, "float32")), axis=-1)
    assert_almost_equal(out2, expect, rtol=1e-4, atol=1e-5)
    out3 = nd.L2Normalization(nd.array(x))
    flat = x.reshape(2, -1)
    expect3 = (flat / np.sqrt((flat ** 2).sum(1, keepdims=True) + 1e-10)).reshape(x.shape)
    assert_almost_equal(out3, expect3, rtol=1e-4, atol=1e-5)


def test_deconvolution_shape():
    x = nd.array(np.random.rand(1, 3, 4, 4).astype("float32"))
    w = nd.array(np.random.rand(3, 2, 3, 3).astype("float32"))
    out = nd.Deconvolution(x, w, kernel=(3, 3), stride=(2, 2), num_filter=2)
    # (i-1)*s - 2p + k = 3*2 + 3 = 9
    assert out.shape == (1, 2, 9, 9)
    out = nd.Deconvolution(x, w, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           adj=(1, 1), num_filter=2)
    assert out.shape == (1, 2, 8, 8)


def test_regression_outputs():
    data = nd.array([[1.0, 2.0]])
    label = nd.array([[0.5, 1.0]])
    out = nd.LinearRegressionOutput(data, label)
    assert_almost_equal(out, data.asnumpy())
    data.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(data, label)
    out.backward()
    assert_almost_equal(data.grad, (data.asnumpy() - label.asnumpy()) / 2, rtol=1e-5)


def test_upsampling_pad():
    x = np.arange(4, dtype="float32").reshape(1, 1, 2, 2)
    out = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest")
    assert out.shape == (1, 1, 4, 4)
    assert_almost_equal(out.asnumpy()[0, 0, :2, :2], [[0, 0], [0, 0]])
    out = nd.Pad(nd.array(x), mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                 constant_value=9.0)
    assert out.shape == (1, 1, 4, 4)
    assert out.asnumpy()[0, 0, 0, 0] == 9.0


def test_multisample_ops():
    """Per-row parameterized samplers (ref: random/multisample_op.cc)."""
    import numpy as np

    from mxnet_tpu import nd

    mx_alpha = nd.array(np.array([1.0, 10.0], np.float32))
    mx_beta = nd.array(np.array([1.0, 2.0], np.float32))
    g = nd._sample_gamma(mx_alpha, mx_beta, shape=(2000,))
    assert g.shape == (2, 2000)
    m = g.asnumpy().mean(axis=1)
    np.testing.assert_allclose(m, [1.0, 20.0], rtol=0.15)  # E=αβ

    lam = nd.array(np.array([1.0, 5.0], np.float32))
    e = nd._sample_exponential(lam, shape=(2000,))
    np.testing.assert_allclose(e.asnumpy().mean(axis=1), [1.0, 0.2],
                               rtol=0.15)
    p = nd._sample_poisson(lam, shape=(2000,))
    np.testing.assert_allclose(p.asnumpy().mean(axis=1), [1.0, 5.0],
                               rtol=0.15)

    k = nd.array(np.array([4.0], np.float32))
    pr = nd.array(np.array([0.5], np.float32))
    nb = nd._sample_negative_binomial(k, pr, shape=(4000,))
    # E = k(1-p)/p = 4
    np.testing.assert_allclose(nb.asnumpy().mean(), 4.0, rtol=0.15)

    mu = nd.array(np.array([3.0], np.float32))
    alpha = nd.array(np.array([0.2], np.float32))
    gnb = nd._sample_generalized_negative_binomial(mu, alpha,
                                                   shape=(4000,))
    np.testing.assert_allclose(gnb.asnumpy().mean(), 3.0, rtol=0.15)
