"""Scheduled-HLO overlap measurement (parallel/overlap.py, VERDICT r4
item 7).  The parser must handle both schedule shapes:

* async ``all-reduce-start``/``done`` pairs with compute in flight —
  overlap credited for the flops scheduled between them;
* the sync combined all-reduce this toolchain's TPU schedule actually
  emits — overlap 0, bytes still accounted.

The committed OVERLAP_MEASURED.json must stay consistent with the
parser's sync semantics (it is the fallback the driver's dryrun loads
on CPU-only boxes).
"""
import json
import os

import numpy as np

from mxnet_tpu.parallel.overlap import schedule_overlap_from_text

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_ASYNC_HLO = """
HloModule test

%add.1 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

%fused_matmul (p0: f32[128,128], p1: f32[128,128]) -> f32[128,128] {
  %p0 = f32[128,128] parameter(0)
  %p1 = f32[128,128] parameter(1)
  ROOT %d = f32[128,128] dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

ENTRY %main (x: f32[128,128], g: f32[1000000]) -> f32[128,128] {
  %x = f32[128,128] parameter(0)
  %g = f32[1000000] parameter(1)
  %ar = f32[1000000] all-reduce-start(%g), to_apply=%add.1
  %mm = f32[128,128] fusion(%x, %x), kind=kOutput, calls=%fused_matmul
  %done = f32[1000000] all-reduce-done(%ar)
  ROOT %out = f32[128,128] add(%mm, %mm)
}
"""

_SYNC_HLO = """
HloModule test

%add.1 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (g: f32[1000000]) -> f32[1000000] {
  %g = f32[1000000] parameter(0)
  ROOT %ar = f32[1000000] all-reduce(%g), to_apply=%add.1
}
"""


def test_async_pair_credits_inflight_flops():
    # 4 MB at 45 GB/s ring (n=8): t_comm = 2*(7/8)*4e6/45e9 = 155.6 us.
    # dot flops = 2*128^3 = 4.19 MFLOP; at 1 GFLOP/s rate that is
    # 4.19 ms of hiding -> fully hidden, overlap 1.0.
    out = schedule_overlap_from_text(_ASYNC_HLO, achieved_flops=1e9,
                                     ici_GBps=45.0, n_devices=8)
    assert out["n_async_pairs"] == 1
    assert out["async_bytes"] == 4000000
    assert abs(out["hidden_flops"] - 2 * 128 ** 3) < 1
    assert out["overlap_measured"] == 1.0

    # at an enormous achieved rate the same flops hide almost nothing
    out2 = schedule_overlap_from_text(_ASYNC_HLO, achieved_flops=1e18,
                                      ici_GBps=45.0, n_devices=8)
    assert out2["overlap_measured"] < 0.01


def test_sync_allreduce_hides_nothing():
    out = schedule_overlap_from_text(_SYNC_HLO, achieved_flops=1e12)
    assert out["n_async_pairs"] == 0
    assert out["n_sync_allreduce_bytes"] == 4000000
    assert out["overlap_measured"] == 0.0


def test_committed_measurement_is_loadable_and_consistent():
    path = os.path.join(ROOT, "OVERLAP_MEASURED.json")
    with open(path) as f:
        rec = json.load(f)
    assert rec["overlap_measured"] is not None
    assert rec["n_async_pairs"] + 1 if rec["overlap_measured"] > 0 \
        else rec["overlap_measured"] == 0.0
    # the dryrun program's gradient payload: one combined all-reduce of
    # every resnet18 grad (MULTICHIP_r04 accounted 44.85 MB across the
    # per-layer form; the combiner folds it into ~44.8 MB here)
    total = rec["n_sync_allreduce_bytes"] + rec["async_bytes"]
    assert 30e6 < total < 60e6, total
