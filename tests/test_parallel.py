"""Data-parallel / mesh tests on the 8-device virtual CPU mesh
(tests SURVEY.md §2.3's DP strategy; the reference tested multi-device on
CPU too — tests/python/unittest/test_model_parallel.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, sym
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.mesh import make_mesh, current_device_count
from mxnet_tpu.parallel.dp import FusedTrainStep, shard_batch, replicate


def _need_devices(n):
    if current_device_count() < n:
        pytest.skip("needs %d devices" % n)


def test_make_mesh():
    _need_devices(8)
    mesh = make_mesh((8,), ("dp",))
    assert mesh.axis_names == ("dp",)
    mesh2 = make_mesh((4, 2), ("dp", "mp"))
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        make_mesh((64,), ("dp",))


def test_shard_and_replicate():
    _need_devices(8)
    mesh = make_mesh((8,), ("dp",))
    x = nd.ones((16, 4))
    shard_batch(x, mesh)
    assert "dp" in str(x._data.sharding.spec)
    w = nd.ones((4, 4))
    replicate(w, mesh)
    np.testing.assert_allclose(x.asnumpy(), 1.0)


def test_fused_train_step_dp8():
    _need_devices(8)
    mesh = make_mesh((8,), ("dp",))
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, learning_rate=0.5, momentum=0.9)
    np.random.seed(0)
    X = np.random.rand(32, 10).astype("float32")
    y = (X @ np.arange(10) > 4.5).astype("float32")  # separable rule
    X, y = nd.array(X), nd.array(y)
    losses = []
    for _ in range(30):
        loss, logits = step(X, y)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses
    assert logits.shape == (32, 4)


def test_fused_step_matches_single_device():
    """DP over 8 devices must give the same loss trajectory as 1 device
    (the exact-arithmetic identity style of tests/nightly/dist_sync_kvstore.py)."""
    _need_devices(8)

    def run(mesh):
        np.random.seed(3)
        mx.random.seed(3)
        net = nn.Dense(4, in_units=6)
        net.initialize(mx.init.Xavier())
        step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh, learning_rate=0.1, momentum=0.0)
        X = nd.array(np.random.RandomState(5).rand(16, 6).astype("float32"))
        y = nd.array(np.random.RandomState(6).randint(0, 4, 16).astype("float32"))
        out = [float(step(X, y)[0].asnumpy()) for _ in range(5)]
        return out

    l1 = run(make_mesh((1,), ("dp",)))
    l8 = run(make_mesh((8,), ("dp",)))
    np.testing.assert_allclose(l1, l8, rtol=1e-5, atol=1e-6)


def test_fused_step_with_batchnorm_aux():
    _need_devices(8)
    mesh = make_mesh((8,), ("dp",))
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Dense(2))
    net.initialize(mx.init.Xavier())
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh)
    X = nd.array(np.random.rand(16, 8).astype("float32"))
    y = nd.array(np.random.randint(0, 2, 16).astype("float32"))
    step(X, y)
    rm = [p for name, p in net.collect_params().items()
          if name.endswith("running_mean")][0]
    assert float(np.abs(rm.data().asnumpy()).sum()) > 0, \
        "BN running stats must update through the fused step"


def test_tensor_parallel_sharding():
    _need_devices(8)
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh((4, 2), ("dp", "mp"))
    net = nn.Dense(8, in_units=6)
    net.initialize(mx.init.Xavier())

    def spec(name, shape):
        if name.endswith("weight"):
            return P("mp", None)
        return None

    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, param_spec_fn=spec)
    X = nd.array(np.random.rand(8, 6).astype("float32"))
    y = nd.array(np.random.randint(0, 8, 8).astype("float32"))
    loss, _ = step(X, y)
    assert np.isfinite(float(loss.asnumpy()))
    w = net.weight.data()._data
    assert "mp" in str(w.sharding.spec), w.sharding


def test_module_multi_context():
    """Module(context=[...]) data parallel — reference multi-device Module."""
    _need_devices(8)
    ctxs = [mx.cpu(i) for i in range(8)]
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(data=net, name="softmax")
    X = np.random.rand(64, 10).astype("float32")
    y = (X @ np.arange(10) > 4.5).astype("float32")  # separable
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(symbol=net, context=ctxs)
    mod.fit(it, optimizer="sgd", optimizer_params={"learning_rate": 0.5},
            num_epoch=10)
    score = mod.score(it, "acc")
    assert score[0][1] > 0.85, score


def test_dryrun_entrypoints(monkeypatch):
    # GRAFT_SKIP_SWEEP: the full scaling report (a dozen compile
    # subprocesses) belongs to the driver's dedicated dryrun phase and
    # the slow-marked tests in test_scaling.py; tier-1 pins the dryrun
    # entrypoint itself (mesh build + dp x mp fused step) inside budget
    monkeypatch.setenv("GRAFT_SKIP_SWEEP", "1")
    _need_devices(8)
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_scaling_report_full():
    """The full dryrun + scaling report (sweep, controls, bucketing
    accounting, SCALING_r08.json) — the driver-phase behavior."""
    _need_devices(8)
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(ge.__file__)),
                        "SCALING_r08.json")
    with open(path) as f:
        rep = json.load(f)
    assert rep["bucketing"]["bucketed"] is True
    assert len(rep["bucketing"]["buckets"]) > 1
    # per-reduction accounting: >1 gradient reduction, no monolith
    assert len(rep["bucketing"]["per_reduction"]) > 1


def test_fused_step_observes_set_data():
    """Parameter.set_data (checkpoint load path) must be picked up by
    the fused step's version-token fast path."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh
    import jax

    net = gluon.nn.Dense(2)
    net.initialize()
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=make_mesh((1,), ("dp",),
                                         jax.devices()[:1]),
                          learning_rate=0.0, momentum=0.0)
    x = nd.ones((2, 3))
    y = nd.zeros((2,))
    _, logits1 = step(x, y)
    # overwrite the weight via the checkpoint-load path
    net.weight.set_data(nd.zeros((2, 3)))
    net.bias.set_data(nd.zeros((2,)))
    _, logits2 = step(x, y)
    np.testing.assert_allclose(logits2.asnumpy(), 0.0, atol=1e-6)


def test_run_steps_bulk_equals_sequential():
    """K steps inside one scan program (the bulk path, ref:
    engine.set_bulk_size semantics) must match K sequential fused steps
    bit-for-bit, including the per-step RNG fold."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh
    import jax

    def build():
        net = nn.HybridSequential(prefix="bulkeq_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.25),
                    nn.Dense(5))
        net.initialize(mx.init.Xavier())
        mesh = make_mesh((4,), ("dp",), jax.devices()[:4])
        return net, FusedTrainStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
            learning_rate=0.1)

    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(8, 12).astype("float32"))
    y = nd.array(rng.randint(0, 5, 8).astype("float32"))

    net1, s1 = build()
    net1(X)  # settle deferred shapes
    saved = {k: v.data().asnumpy()
             for k, v in net1.collect_params().items()}
    mx.random.seed(11)
    seq = [float(s1(X, y)[0].asnumpy()) for _ in range(4)]

    net2, s2 = build()
    net2(X)
    for k, v in net2.collect_params().items():
        v.set_data(nd.array(saved[k]))
    mx.random.seed(11)
    scan = s2.run_steps(X, y, steps=4).asnumpy()
    np.testing.assert_allclose(seq, scan, rtol=1e-5, atol=1e-6)
    for a, b in zip(s1._param_vals, s2._param_vals):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_run_steps_stacked_batches():
    """run_steps with a leading-K batch dimension consumes one batch
    per step."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh
    import jax

    net = nn.HybridSequential(prefix="bulkst_")
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((4,), ("dp",), jax.devices()[:4])
    step = FusedTrainStep(net, gluon.loss.L2Loss(), mesh=mesh,
                          learning_rate=0.05)
    rng = np.random.RandomState(1)
    Xs = nd.array(rng.randn(3, 8, 6).astype("float32"))
    ys = nd.array(rng.randn(3, 8, 4).astype("float32"))
    losses = step.run_steps(Xs, ys)
    assert losses.shape == (3,)
    l = losses.asnumpy()
    assert np.isfinite(l).all()
