"""Parity-tail tests: mx.text, mx.name, mx.engine, mx.rtc (Pallas),
mx.contrib.autograd, torch bridge, test_utils harness, tools
(parse_log, bandwidth)."""
import collections
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


# ------------------------------------------------------------------ text
def test_token_indexer():
    counter = collections.Counter(
        {"the": 10, "cat": 5, "sat": 5, "rare": 1})
    idx = mx.text.TokenIndexer(counter, min_freq=2,
                               reserved_tokens=["<pad>"])
    assert idx.unknown_token == "<unk>"
    assert idx.idx_to_token[0] == "<unk>"
    assert idx.idx_to_token[1] == "<pad>"
    assert idx.to_indices("the") == 2  # most frequent first
    assert "rare" not in idx.token_to_idx  # below min_freq
    assert idx.to_indices(["cat", "never-seen"])[1] == 0
    assert idx.to_tokens(2) == "the"
    assert len(idx) == 5


def test_token_indexer_most_freq_count():
    counter = collections.Counter({"a": 5, "b": 4, "c": 3, "d": 2})
    idx = mx.text.TokenIndexer(counter, most_freq_count=2)
    assert len(idx) == 3  # unk + 2


def test_glove_embedding_and_glossary(tmp_path):
    p = tmp_path / "glove.txt"
    p.write_text("hello 0.1 0.2 0.3\nworld 0.4 0.5 0.6\n")
    emb = mx.text.GloVe(pretrained_file_path=str(p))
    assert emb.vec_len == 3
    v = emb.get_vecs_by_tokens("world").asnumpy()
    np.testing.assert_allclose(v, [0.4, 0.5, 0.6], rtol=1e-6)
    # unknown → zeros
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("nope").asnumpy(), 0.0)
    # batch lookup
    m = emb.get_vecs_by_tokens(["hello", "world"]).asnumpy()
    assert m.shape == (2, 3)
    # update
    emb.update_token_vectors("hello", nd.array([1.0, 1.0, 1.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), 1.0)
    # glossary composes counter vocab + embedding vectors
    counter = collections.Counter({"world": 3, "unseen": 2})
    gl = mx.text.Glossary(counter, emb)
    assert gl.vec_len == 3
    np.testing.assert_allclose(
        gl.get_vecs_by_tokens("world").asnumpy(), [0.4, 0.5, 0.6],
        rtol=1e-6)


def test_fasttext_header_and_custom(tmp_path):
    p = tmp_path / "ft.vec"
    p.write_text("2 3\nab 1 2 3\ncd 4 5 6\n")
    emb = mx.text.FastText(pretrained_file_path=str(p))
    assert emb.vec_len == 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("cd").asnumpy(), [4, 5, 6])
    p2 = tmp_path / "custom.txt"
    p2.write_text("x,1,2\ny,3,4\n")
    emb2 = mx.text.CustomEmbedding(pretrained_file_path=str(p2),
                                   elem_delim=",")
    assert emb2.vec_len == 2
    created = mx.text.embedding.create(
        "glove", pretrained_file_path=str(tmp_path / "ft.vec"))
    assert isinstance(created, mx.text.GloVe)


def test_embedding_missing_file():
    with pytest.raises(OSError):
        mx.text.GloVe(pretrained_file_path="/nonexistent/file.txt")


def test_count_tokens_from_str():
    c = mx.text.utils.count_tokens_from_str("a b b\nc a", to_lower=True)
    assert c == collections.Counter({"a": 2, "b": 2, "c": 1})
    # regex-metacharacter delimiters must be escaped, not interpreted
    c = mx.text.utils.count_tokens_from_str("a.b c", seq_delim=".")
    assert c == collections.Counter({"a": 1, "b": 1, "c": 1})


# ---------------------------------------------------------------- naming
def test_name_prefix_scope():
    with mx.name.Prefix("net_"):
        s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
        named = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                      num_hidden=2, name="fc")
    assert s.name == "net_fullyconnected0"  # reference name grammar
    assert named.name == "net_fc"  # Prefix applies to explicit names too
    s2 = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
    assert not s2.name.startswith("net_")


def test_name_manager_scope_resets_counters():
    """A fresh `with NameManager():` restarts auto-name counters, so
    checkpoint-deterministic rebuilds get identical parameter names."""
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
    with mx.name.NameManager():
        a = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
    with mx.name.NameManager():
        b = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
    assert a.name == b.name == "fullyconnected0"


# ---------------------------------------------------------------- engine
def test_engine_bulk_api():
    prev = mx.engine.set_bulk_size(30)
    assert mx.engine.set_bulk_size(prev) == 30
    with mx.engine.bulk(5):
        x = nd.ones((4,)) + 1
    assert float(x.sum().asnumpy()) == 8.0


# ------------------------------------------------------------------- rtc
def test_rtc_pallas_module():
    def axpy(a, x, y):
        # plain jax body is a valid "kernel" for the module API; a
        # pl.pallas_call body plugs in identically
        return a * x + y

    mod = mx.rtc.PallasModule({"axpy": axpy})
    k = mod.get_kernel("axpy")
    (out,) = k.launch([2.0, nd.ones((4,)), nd.ones((4,))])
    np.testing.assert_allclose(out.asnumpy(), 3.0)
    with pytest.raises(mx.MXNetError):
        mod.get_kernel("nope")
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaModule("__global__ void f() {}")


def test_rtc_pallas_real_kernel():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def add_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    def add(x, y):
        return pl.pallas_call(
            add_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=jax.default_backend() == "cpu",
        )(x, y)

    mod = mx.rtc.PallasModule()
    mod.add_kernel("add", add)
    (out,) = mod.get_kernel("add").launch(
        [nd.ones((8, 128)), nd.ones((8, 128))])
    np.testing.assert_allclose(out.asnumpy(), 2.0)


# ------------------------------------------------------- contrib.autograd
def test_contrib_autograd_v1():
    from mxnet_tpu.contrib import autograd as ag1

    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))

    def f(x):
        return (x * x).sum()

    g = ag1.grad(f)(x)
    np.testing.assert_allclose(g[0].asnumpy(), 2 * x.asnumpy())
    grads, loss = ag1.grad_and_loss(f)(x)
    np.testing.assert_allclose(float(loss.asnumpy()), 14.0)
    with ag1.train_section():
        assert mx.autograd.is_recording()
    assert not mx.autograd.is_recording()


# ---------------------------------------------------------- torch bridge
def test_torch_bridge():
    from mxnet_tpu import torch as mxt

    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    t = mxt.to_torch(x)
    assert tuple(t.shape) == (2, 3)
    np.testing.assert_allclose(t.numpy(), x.asnumpy())
    back = mxt.from_torch(t * 2)
    np.testing.assert_allclose(back.asnumpy(), 2 * x.asnumpy())


# ------------------------------------------------------------- test_utils
def test_check_symbolic_forward_backward():
    from mxnet_tpu import test_utils as tu

    data = mx.sym.Variable("data")
    out = data * 2 + 1
    x = np.random.rand(3, 4).astype(np.float32)
    tu.check_symbolic_forward(out, [x], [2 * x + 1])
    tu.check_symbolic_backward(out, [x], [np.ones_like(x)],
                               {"data": 2 * np.ones_like(x)})


def test_rand_sparse_ndarray():
    from mxnet_tpu import test_utils as tu

    arr, dense = tu.rand_sparse_ndarray((8, 4), "row_sparse",
                                        density=0.5)
    np.testing.assert_allclose(arr.todense().asnumpy(), dense)
    arr, dense = tu.rand_sparse_ndarray((6, 5), "csr", density=0.3)
    np.testing.assert_allclose(arr.todense().asnumpy(), dense)


# ------------------------------------------------------------------ tools
def test_parse_log(tmp_path):
    sys.path.insert(0, os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "tools")))
    import parse_log

    log = (
        "INFO:root:Epoch[0] Batch [50]\tSpeed: 5129.15 samples/sec"
        "\taccuracy=0.095294\n"
        "INFO:root:Epoch[0] Train-accuracy=0.106667\n"
        "INFO:root:Epoch[0] Time cost=1.992\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.110000\n"
        "INFO:root:Epoch[1] Batch [50]\tSpeed: 32072.67 samples/sec"
        "\taccuracy=0.630000\n"
        "INFO:root:Epoch[1] Train-accuracy=1.000000\n"
        "INFO:root:Epoch[1] Time cost=0.186\n"
        "INFO:root:Epoch[1] Validation-accuracy=1.000000\n")
    epochs = parse_log.parse(log.splitlines())
    assert epochs[0]["train"]["accuracy"] == pytest.approx(0.106667)
    assert epochs[1]["val"]["accuracy"] == 1.0
    assert epochs[0]["speed"] == [pytest.approx(5129.15)]
    assert epochs[1]["time"] == pytest.approx(0.186)


@pytest.mark.slow
def test_bandwidth_measure_local():
    tools = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         "..", "tools"))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=os.path.abspath(os.path.join(
                   os.path.dirname(__file__), "..")))
    out = subprocess.run(
        [sys.executable, os.path.join(tools, "bandwidth", "measure.py"),
         "--kv-store", "local", "--num-layers", "3", "--size", "65536",
         "--iters", "3"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "GB/s" in out.stdout
