"""Plugin creator bridges (ref: plugin/warpctc, plugin/caffe,
plugin/torch) — functional checks:

* the caffe_net.py MLP composition (example/caffe/caffe_net.py:28-35)
  binds, trains, and reaches >0.9 accuracy on a separable problem;
* caffe Pooling keeps caffe's ceil-mode output shapes
  (pooling_layer.cpp), which FLOOR-mode frameworks get wrong;
* WarpCTC's backward equals finite differences of the summed CTC cost
  (warpctc-inl.h:208 compute_ctc_loss contract: in_grad = dcost/dact,
  out_grad ignored);
* TorchModule/TorchCriterion match REAL pytorch (an independent oracle
  for the lua-subset semantics, incl. ClassNLL's 1-based labels).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _train_caffe_mlp():
    rng = np.random.RandomState(0)
    n, d = 256, 8
    X = rng.randn(n, d).astype("float32")
    w_true = rng.randn(d, 3).astype("float32")
    y = np.argmax(X @ w_true, axis=1).astype("float32")

    data = mx.sym.Variable("data")
    fc1 = mx.sym.CaffeOp(data_0=data, num_weight=2, name="fc1",
                         prototxt='layer{type:"InnerProduct" '
                                  'inner_product_param{num_output: 32} }')
    act1 = mx.sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}')
    fc2 = mx.sym.CaffeOp(data_0=act1, num_weight=2, name="fc2",
                         prototxt='layer{type:"InnerProduct" '
                                  'inner_product_param{num_output: 3}}')
    out = mx.sym.CaffeLoss(data=fc2, label=mx.sym.Variable("label"),
                           grad_scale=1, name="softmax",
                           prototxt='layer{type:"SoftmaxWithLoss"}')

    mod = mx.mod.Module(out, data_names=("data",), label_names=("label",))
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="label")
    mod.fit(it, num_epoch=30, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5),),
            initializer=mx.init.Xavier())
    it.reset()
    preds = mod.predict(it).asnumpy()
    return float((np.argmax(preds, axis=1) == y).mean())


def test_caffe_mlp_trains():
    acc = _train_caffe_mlp()
    assert acc > 0.9, "caffe-bridge MLP stuck at %.3f" % acc


def test_caffe_pooling_ceil_mode():
    # caffe: out = ceil((H + 2p - k) / s) + 1  ->  H=5,k=2,s=2 gives 3
    # (floor-mode frameworks give 2)
    x = mx.sym.Variable("x")
    pool = mx.sym.CaffeOp(
        data_0=x, prototxt='layer{type:"Pooling" pooling_param '
                           '{ pool: MAX kernel_size: 2 stride: 2}}')
    _, out_shapes, _ = pool.infer_shape(x=(1, 1, 5, 5))
    assert out_shapes[0] == (1, 1, 3, 3)
    ex = pool.bind(mx.cpu(), {"x": mx.nd.array(
        np.arange(25, dtype="float32").reshape(1, 1, 5, 5))})
    got = ex.forward()[0].asnumpy()
    expect = np.array([[6., 8., 9.], [16., 18., 19.], [21., 23., 24.]],
                      dtype="float32")
    np.testing.assert_allclose(got[0, 0], expect)


def test_warpctc_forward_softmax_and_grad():
    rng = np.random.RandomState(1)
    T, N, A, L = 6, 2, 5, 2
    acts = rng.randn(T * N, A).astype("float32")
    labels = np.array([1, 2, 3, 0], dtype="float32")  # blank-0 padded

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    sym = mx.sym.WarpCTC(data=data, label=label, label_length=L,
                         input_length=T)
    args = {"data": mx.nd.array(acts), "label": mx.nd.array(labels)}
    grads = {"data": mx.nd.zeros((T * N, A)),
             "label": mx.nd.zeros((N * L,))}
    ex = sym.bind(mx.cpu(), args, args_grad=grads,
                  grad_req={"data": "write", "label": "null"})
    out = ex.forward(is_train=True)[0].asnumpy()
    # forward = row softmax (warpctc-inl.h:95)
    e = np.exp(acts - acts.max(axis=1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True),
                               rtol=1e-5, atol=1e-6)

    ex.backward([mx.nd.ones((T * N, A))])
    got = ex.grad_dict["data"].asnumpy()

    # finite differences of the summed CTC cost
    from mxnet_tpu.ops.contrib import _ctc_loss

    def cost(a):
        import jax.numpy as jnp

        act = jnp.asarray(a.reshape(T, N, A), dtype=jnp.float32)
        lab = jnp.asarray(labels.reshape(N, L))
        return float(np.sum(np.asarray(
            _ctc_loss(act, lab, blank_label="first"))))

    eps = 1e-3
    for idx in [(0, 0), (3, 2), (11, 4)]:
        ap = acts.copy()
        ap[idx] += eps
        am = acts.copy()
        am[idx] -= eps
        fd = (cost(ap) - cost(am)) / (2 * eps)
        assert abs(fd - got[idx]) < 5e-3, (idx, fd, got[idx])


@pytest.mark.skipif(not pytest.importorskip("torch"), reason="no torch")
def test_torch_bridge_matches_pytorch():
    import torch as th

    rng = np.random.RandomState(2)
    B, D, C = 4, 6, 3
    x = rng.randn(B, D).astype("float32")
    w = rng.randn(C, D).astype("float32")
    b = rng.randn(C).astype("float32")
    y = np.array([1, 0, 2, 1], dtype="float32")  # 0-based; lua adds 1

    xs = mx.sym.Variable("x")
    lin = mx.sym.TorchModule(data_0=xs, lua_string="nn.Linear(%d, %d)"
                             % (D, C), num_data=1, num_params=2,
                             num_outputs=1, name="lin")
    lsm = mx.sym.TorchModule(data_0=lin, lua_string="nn.LogSoftMax()",
                             num_data=1, num_params=0, num_outputs=1)
    crit = mx.sym.TorchCriterion(data=lsm, label=mx.sym.Variable("lab"),
                                 lua_string="nn.ClassNLLCriterion()")
    args = {"x": mx.nd.array(x), "lin_weight": mx.nd.array(w),
            "lin_bias": mx.nd.array(b), "lab": mx.nd.array(y + 1.0)}
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
    ex = crit.bind(mx.cpu(), args, args_grad=grads,
                   grad_req={"x": "write", "lin_weight": "write",
                             "lin_bias": "write", "lab": "null"})
    loss = ex.forward(is_train=True)[0].asnumpy()

    tx = th.tensor(x, requires_grad=True)
    tw = th.tensor(w, requires_grad=True)
    tb = th.tensor(b, requires_grad=True)
    tloss = th.nn.functional.nll_loss(
        th.log_softmax(th.nn.functional.linear(tx, tw, tb), dim=1),
        th.tensor(y.astype("int64")))
    np.testing.assert_allclose(loss, [tloss.item()], rtol=1e-5, atol=1e-6)

    ex.backward([mx.nd.ones((1,))])
    tloss.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               tx.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ex.grad_dict["lin_weight"].asnumpy(),
                               tw.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ex.grad_dict["lin_bias"].asnumpy(),
                               tb.grad.numpy(), rtol=1e-4, atol=1e-5)
