"""Profiler / Monitor / visualization tests
(models: tests/python/unittest/test_profiler.py, test_monitor-style usage
in the reference)."""
import json
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, profiler


def test_profiler_records_imperative_ops(tmp_path):
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname, profile_all=True)
    profiler.set_state("run")
    a = nd.ones((8, 8))
    b = nd.ones((8, 8))
    c = nd.dot(a, b)
    d = (c + 1).sum()
    d.wait_to_read()
    profiler.set_state("stop")
    out = profiler.dump()
    assert out == fname
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    names = [e["name"] for e in events]
    assert "dot" in names
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 0
        assert e["ts"] >= 0


def test_profiler_pause_resume(tmp_path):
    fname = str(tmp_path / "p.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    profiler.pause()
    _ = nd.ones((4,)) + 1
    profiler.resume()
    x = nd.ones((4,)) * 2
    x.wait_to_read()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "_mul_scalar" in names and "_plus_scalar" not in names


def test_profiler_symbolic_spans(tmp_path):
    fname = str(tmp_path / "s.json")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    out = mx.sym.SoftmaxOutput(fc, name="sm")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 8))
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    exe.forward(is_train=True)
    exe.backward()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert any(n.startswith("Forward") for n in names)
    assert any(n.startswith("Backward") for n in names)


def test_monitor_taps_internal_outputs():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=2)
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    exe = out.simple_bind(ctx=mx.cpu(), data=(4, 16))
    exe.arg_dict["data"][:] = np.random.rand(4, 16)
    exe.arg_dict["fc1_weight"][:] = np.random.rand(8, 16) * 0.1
    exe.arg_dict["fc2_weight"][:] = np.random.rand(2, 8) * 0.1

    mon = mx.Monitor(interval=1, pattern=".*")
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True)
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert "fc1_output" in names
    assert "relu1_output" in names
    assert "softmax_output" in names
    # param stats folded in at toc
    assert "fc1_weight" in names
    # stat values are parseable floats
    for _, k, v in rows:
        float(v.strip().split("\t")[0])


def test_monitor_pattern_and_interval():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=4)
    exe = fc1.simple_bind(ctx=mx.cpu(), data=(2, 4))
    mon = mx.Monitor(interval=2, pattern="fc1.*")
    mon.install(exe)
    mon.tic()  # step 0: active
    exe.forward()
    rows0 = mon.toc()
    assert all(k.startswith(("fc1", "grad_fc1")) for _, k, _ in rows0)
    mon.tic()  # step 1: inactive (interval=2)
    exe.forward()
    assert mon.toc() == []


def test_monitor_fires_in_module_fit():
    """Monitor must tap internals through the fit() train step
    (run_train_step path), not just manual exe.forward()."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    x = np.random.rand(8, 4).astype(np.float32)
    y = (x.sum(axis=1) > 2).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    seen = []
    mon = mx.Monitor(interval=1, pattern="fc1.*")
    orig = mon.stat_helper

    def spy(name, arr):
        seen.append(name)
        orig(name, arr)

    mon.stat_helper = spy
    mod.fit(it, num_epoch=1, monitor=mon,
            optimizer_params={"learning_rate": 0.1})
    assert "fc1_output" in seen


def test_monitor_single_fire_manual_forward_backward():
    """Manual forward()+backward() must fire each stat exactly once."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 4))
    mon = mx.Monitor(interval=1, pattern="fc1_output")
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True)
    exe.backward(out_grads=mx.nd.ones((2, 2)))
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert names.count("fc1_output") == 1


def test_custom_op_sees_is_train():
    import mxnet_tpu.operator as mxop

    seen = []

    @mxop.register("trainspy")
    class TrainSpyProp(mxop.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Op(mxop.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    seen.append(bool(is_train))
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])
            return Op()

    x = mx.nd.ones((2, 2))
    mx.nd.Custom(x, op_type="trainspy").wait_to_read()
    assert seen[-1] is False  # outside autograd: inference
    with mx.autograd.record():
        mx.nd.Custom(x, op_type="trainspy").wait_to_read()
    assert seen[-1] is True  # recording implies training


def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=2)
    total = mx.viz.print_summary(fc2, shape={"data": (1, 16)})
    cap = capsys.readouterr().out
    assert "fc1" in cap and "fc2" in cap
    # fc1: 16*8 + 8; fc2: 8*2 + 2
    assert total == 16 * 8 + 8 + 8 * 2 + 2


def test_plot_network_gated():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    try:
        import graphviz  # noqa: F401
        has_gv = True
    except ImportError:
        has_gv = False
    if has_gv:
        dot = mx.viz.plot_network(fc, shape={"data": (1, 4)})
        assert "fc" in dot.source
    else:
        import pytest
        with pytest.raises(ImportError):
            mx.viz.plot_network(fc)
