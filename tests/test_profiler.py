"""Profiler / Monitor / visualization tests
(models: tests/python/unittest/test_profiler.py, test_monitor-style usage
in the reference)."""
import json
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, profiler


def test_profiler_records_imperative_ops(tmp_path):
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname, profile_all=True)
    profiler.set_state("run")
    a = nd.ones((8, 8))
    b = nd.ones((8, 8))
    c = nd.dot(a, b)
    d = (c + 1).sum()
    d.wait_to_read()
    profiler.set_state("stop")
    out = profiler.dump()
    assert out == fname
    with open(fname) as f:
        trace = json.load(f)
    # metadata rows (process_name/thread_name) ride along like the
    # reference's traces; op spans are the ph:"X" events
    events = [e for e in trace["traceEvents"] if e["ph"] != "M"]
    names = [e["name"] for e in events]
    assert "dot" in names
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 0
        assert e["ts"] >= 0


def test_profiler_pause_resume(tmp_path):
    fname = str(tmp_path / "p.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    profiler.pause()
    _ = nd.ones((4,)) + 1
    profiler.resume()
    x = nd.ones((4,)) * 2
    x.wait_to_read()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "_mul_scalar" in names and "_plus_scalar" not in names


def test_profiler_symbolic_spans(tmp_path):
    fname = str(tmp_path / "s.json")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    out = mx.sym.SoftmaxOutput(fc, name="sm")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 8))
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    exe.forward(is_train=True)
    exe.backward()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert any(n.startswith("Forward") for n in names)
    assert any(n.startswith("Backward") for n in names)


def test_monitor_taps_internal_outputs():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=2)
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    exe = out.simple_bind(ctx=mx.cpu(), data=(4, 16))
    exe.arg_dict["data"][:] = np.random.rand(4, 16)
    exe.arg_dict["fc1_weight"][:] = np.random.rand(8, 16) * 0.1
    exe.arg_dict["fc2_weight"][:] = np.random.rand(2, 8) * 0.1

    mon = mx.Monitor(interval=1, pattern=".*")
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True)
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert "fc1_output" in names
    assert "relu1_output" in names
    assert "softmax_output" in names
    # param stats folded in at toc
    assert "fc1_weight" in names
    # stat values are parseable floats
    for _, k, v in rows:
        float(v.strip().split("\t")[0])


def test_monitor_pattern_and_interval():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=4)
    exe = fc1.simple_bind(ctx=mx.cpu(), data=(2, 4))
    mon = mx.Monitor(interval=2, pattern="fc1.*")
    mon.install(exe)
    mon.tic()  # step 0: active
    exe.forward()
    rows0 = mon.toc()
    assert all(k.startswith(("fc1", "grad_fc1")) for _, k, _ in rows0)
    mon.tic()  # step 1: inactive (interval=2)
    exe.forward()
    assert mon.toc() == []


def test_monitor_fires_in_module_fit():
    """Monitor must tap internals through the fit() train step
    (run_train_step path), not just manual exe.forward()."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    x = np.random.rand(8, 4).astype(np.float32)
    y = (x.sum(axis=1) > 2).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    seen = []
    mon = mx.Monitor(interval=1, pattern="fc1.*")
    orig = mon.stat_helper

    def spy(name, arr):
        seen.append(name)
        orig(name, arr)

    mon.stat_helper = spy
    mod.fit(it, num_epoch=1, monitor=mon,
            optimizer_params={"learning_rate": 0.1})
    assert "fc1_output" in seen


def test_monitor_single_fire_manual_forward_backward():
    """Manual forward()+backward() must fire each stat exactly once."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 4))
    mon = mx.Monitor(interval=1, pattern="fc1_output")
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True)
    exe.backward(out_grads=mx.nd.ones((2, 2)))
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert names.count("fc1_output") == 1


def test_custom_op_sees_is_train():
    import mxnet_tpu.operator as mxop

    seen = []

    @mxop.register("trainspy")
    class TrainSpyProp(mxop.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Op(mxop.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    seen.append(bool(is_train))
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])
            return Op()

    x = mx.nd.ones((2, 2))
    mx.nd.Custom(x, op_type="trainspy").wait_to_read()
    assert seen[-1] is False  # outside autograd: inference
    with mx.autograd.record():
        mx.nd.Custom(x, op_type="trainspy").wait_to_read()
    assert seen[-1] is True  # recording implies training


def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=2)
    total = mx.viz.print_summary(fc2, shape={"data": (1, 16)})
    cap = capsys.readouterr().out
    assert "fc1" in cap and "fc2" in cap
    # fc1: 16*8 + 8; fc2: 8*2 + 2
    assert total == 16 * 8 + 8 + 8 * 2 + 2


def test_plot_network_gated():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=2)
    try:
        import graphviz  # noqa: F401
        has_gv = True
    except ImportError:
        has_gv = False
    if has_gv:
        dot = mx.viz.plot_network(fc, shape={"data": (1, 4)})
        assert "fc" in dot.source
    else:
        import pytest
        with pytest.raises(ImportError):
            mx.viz.plot_network(fc)


def test_monitor_grad_stats_populated():
    """toc() must wait on grad buffers before reading them — grad stats
    appear exactly once per tapped parameter after backward."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=3)
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 4))
    exe.arg_dict["data"][:] = np.random.rand(2, 4)
    exe.arg_dict["fc1_weight"][:] = np.random.rand(3, 4)
    mon = mx.Monitor(interval=1, pattern=".*")
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=True)
    exe.backward(out_grads=mx.nd.ones((2, 3)))
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert names.count("grad_fc1_weight") == 1
    assert names.count("grad_fc1_bias") == 1
    for _, k, v in rows:
        if k.startswith("grad_"):
            float(v.strip().split("\t")[0])  # real, settled value


def test_dumps_aggregate_math(tmp_path):
    """dumps()/summary(): count/total/min/max/avg over repeated ops."""
    profiler = mx.profiler
    profiler.set_config(filename=str(tmp_path / "agg.json"))
    profiler.set_state("run")
    for _ in range(3):
        nd.dot(nd.ones((16, 16)), nd.ones((16, 16))).wait_to_read()
    profiler.set_state("stop")
    s = profiler.summary()["spans"]["operator"]["dot"]
    assert s["count"] == 3
    assert s["min_ms"] <= s["avg_ms"] <= s["max_ms"]
    np.testing.assert_allclose(s["avg_ms"], s["total_ms"] / 3, rtol=1e-6)
    table = profiler.dumps()
    assert "Profile Statistics" in table
    assert "dot" in table
    # reset=True clears the accumulators
    profiler.dumps(reset=True)
    assert profiler.summary()["spans"] == {}


def test_counter_marker_event_shapes(tmp_path):
    fname = str(tmp_path / "cm.json")
    profiler = mx.profiler
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    domain = profiler.Domain("app")
    ctr = profiler.Counter(domain, "requests", 10)
    ctr.increment(5)
    ctr -= 3
    assert ctr.value == 12
    profiler.Marker(domain, "phase_end").mark(scope="process")
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    counters = [e for e in events if e["ph"] == "C"
                and e["name"] == "requests"]
    assert [e["args"]["requests"] for e in counters] == [10, 15, 12]
    assert all(e["cat"] == "app" for e in counters)
    markers = [e for e in events if e["ph"] == "i"]
    assert [e["name"] for e in markers] == ["phase_end"]
    assert markers[0]["s"] == "p"
    # counters fold into the aggregate stats as values, not times
    c = profiler.summary()["counters"]["app"]["requests"]
    assert c["count"] == 3 and c["min"] == 10 and c["max"] == 15


def test_profile_memory_counters_on_cpu(tmp_path):
    """profile_memory=True must produce ph:'C' memory counters even on
    the CPU backend (live-buffer fallback for memory_stats()=None)."""
    fname = str(tmp_path / "mem.json")
    profiler = mx.profiler
    profiler.set_config(filename=fname, profile_memory=True)
    profiler.set_state("run")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 8))
    exe.forward(is_train=True)
    exe.backward(out_grads=mx.nd.ones((2, 4)))
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    mem = [e for e in events if e["ph"] == "C" and e["cat"] == "memory"]
    in_use = [e for e in mem if e["name"] == "memory:bytes_in_use"]
    peak = [e for e in mem if e["name"] == "memory:peak_bytes_in_use"]
    assert len(in_use) >= 2 and len(peak) >= 2  # around fwd AND bwd
    for e in mem:
        assert e["args"][e["name"]] > 0
    # peak is monotone and >= every in_use sample
    peaks = [e["args"]["memory:peak_bytes_in_use"] for e in peak]
    assert peaks == sorted(peaks)
    assert max(v["args"]["memory:bytes_in_use"] for v in in_use) <= peaks[-1]


def test_rank_suffixed_dump(tmp_path, monkeypatch):
    """Multi-worker env => dump writes profile_rank{K}.json, pid=rank."""
    monkeypatch.setenv("DMLC_WORKER_ID", "1")
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    fname = str(tmp_path / "profile.json")
    profiler = mx.profiler
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    (nd.ones((4,)) * 2).wait_to_read()
    profiler.set_state("stop")
    out = profiler.dump()
    expect = str(tmp_path / "profile_rank1.json")
    assert out == expect and os.path.exists(expect)
    with open(expect) as f:
        events = json.load(f)["traceEvents"]
    assert events and all(e["pid"] == 1 for e in events)
    pnames = [e for e in events if e.get("ph") == "M"
              and e["name"] == "process_name"]
    assert pnames and pnames[0]["args"]["name"] == "rank 1"


def test_fit_telemetry_end_to_end(tmp_path):
    """Acceptance: a Module.fit mini-run yields a non-empty aggregate
    table, a memory counter event, and a kvstore comms span."""
    fname = str(tmp_path / "fit.json")
    profiler = mx.profiler
    profiler.set_config(filename=fname, profile_all=True,
                        profile_memory=True)
    profiler.set_state("run")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=2)
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    x = np.random.rand(8, 4).astype(np.float32)
    y = (x.sum(axis=1) > 2).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    mod.fit(it, num_epoch=1, kvstore="local",
            optimizer_params={"learning_rate": 0.1})
    profiler.set_state("stop")
    profiler.dump()

    table = profiler.dumps()
    assert "Profile Statistics" in table
    summ = profiler.summary()
    spans = summ["spans"]
    # per-op aggregates from the executor + optimizer + comms + io
    # (fit drives the FUSED fwd+vjp step, stamped as the Backward span)
    assert any(n.startswith("Backward") for n in spans.get("symbolic", {}))
    assert spans["symbolic"]["Backward<softmax_output>"]["count"] == 2
    assert "KVStore::Push" in spans.get("comms", {})
    assert spans["comms"]["KVStore::Push"]["count"] >= 2
    assert "SGD::update" in spans.get("optimizer", {})
    assert any(n.endswith("::next") for n in spans.get("io", {}))

    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    assert any(e["ph"] == "C" and e["cat"] == "memory" for e in events)
    assert any(e["ph"] == "X" and e["cat"] == "comms"
               and e["name"] == "KVStore::Push" for e in events)
    push = next(e for e in events if e.get("cat") == "comms"
                and e["name"] == "KVStore::Push")
    assert push["args"]["bytes"] > 0
    # cumulative bytes-on-the-wire counter rode along
    assert any(e["ph"] == "C" and e["name"] == "kvstore:push_bytes"
               for e in events)
