"""PS failure semantics: liveness, timeouts, recovery, authentication.

ref: src/kvstore/kvstore_dist.h:56 (is_recovery rejoin),
:113-121 (GetDeadNodes liveness) — the reference's ps-lite gives it
heartbeats + dead-node queries + rejoin; these tests pin the same
contract on our scheduler/transport, including the case the reference
handles via ps-lite timeouts: a *hung* (SIGSTOP'd, not closed) server
must surface as an error within the request timeout, never a worker
hang."""
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import _ps
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore import KVStoreDist

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_request_timeout_on_hung_peer():
    """A peer that accepts but never responds must raise within the
    request timeout, not block forever."""
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    addr = lst.getsockname()

    def accept_and_sit():
        conn, _ = lst.accept()
        time.sleep(20)
        conn.close()

    t = threading.Thread(target=accept_and_sit, daemon=True)
    t.start()
    c = _ps.Client(addr)
    t0 = time.time()
    with pytest.raises(ConnectionError, match="no response"):
        c.request({"op": "pull", "key": "k"}, timeout=1.5)
    assert time.time() - t0 < 10
    c.close()
    lst.close()


def test_closed_peer_raises_not_hangs():
    """A peer that dies (connection closed) surfaces as MXNetError via
    the worker's response check."""
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    addr = lst.getsockname()

    def accept_one_then_close():
        conn, _ = lst.accept()
        msg = _ps.recv_msg(conn)
        _ps.send_msg(conn, {"ok": True})
        conn.close()

    t = threading.Thread(target=accept_one_then_close, daemon=True)
    t.start()
    c = _ps.Client(addr)
    assert KVStoreDist._req(c, {"op": "init"}) == {"ok": True}
    time.sleep(0.2)
    with pytest.raises(MXNetError, match="connection lost"):
        KVStoreDist._req(c, {"op": "push"})
    c.close()
    lst.close()


def test_hmac_authentication(monkeypatch):
    """With MXNET_PS_SECRET set, frames authenticate; a tampered frame
    is rejected instead of reaching pickle.loads."""
    monkeypatch.setenv("MXNET_PS_SECRET", "s3cret")
    a, b = socket.socketpair()
    _ps.send_msg(a, {"op": "x", "v": 1})
    assert _ps.recv_msg(b) == {"op": "x", "v": 1}
    # tamper: flip a payload byte after the tag
    import pickle
    import struct

    payload = pickle.dumps({"op": "evil"})
    tag = b"\x00" * _ps._TAG_LEN
    a.sendall(struct.pack("<Q", len(payload)) + tag + payload)
    with pytest.raises(ConnectionError, match="authentication"):
        _ps.recv_msg(b)
    a.close()
    b.close()


def test_scheduler_liveness_and_recovery():
    """Heartbeat aging drives dead_nodes; a recovering node reclaims its
    rank without shifting assignment."""
    port = _free_port()
    os.environ["DMLC_PS_ROOT_URI"] = "127.0.0.1"
    os.environ["DMLC_PS_ROOT_PORT"] = str(port)
    try:
        sched = _ps.Scheduler(port, num_servers=1, num_workers=1)
        t = threading.Thread(target=sched.run, daemon=True)
        t.start()

        srv = _ps.Client(("127.0.0.1", port))
        assert srv.request({"op": "register_server",
                            "addr": ("127.0.0.1", 1)})["rank"] == 0
        wrk = _ps.Client(("127.0.0.1", port))
        resp = wrk.request({"op": "register_worker"})
        assert resp["rank"] == 0
        assert resp["servers"] == [("127.0.0.1", 1)]

        # both heartbeated at registration: nothing dead at 60s horizon
        assert wrk.request({"op": "dead_nodes",
                            "timeout": 60})["dead"] == []
        time.sleep(1.1)
        # nobody has beaten for >1s: both show up at a 1s horizon
        dead = wrk.request({"op": "dead_nodes", "timeout": 1.0})["dead"]
        assert "server:0" in dead and "worker:0" in dead
        # a beat brings the server back
        srv.request({"op": "heartbeat", "role": "server", "rank": 0})
        dead = wrk.request({"op": "dead_nodes", "timeout": 1.0})["dead"]
        assert "server:0" not in dead and "worker:0" in dead

        # recovery rejoin: a "restarted" worker reclaims rank 0 and the
        # fresh-rank counter is untouched
        wrk2 = _ps.Client(("127.0.0.1", port))
        resp2 = wrk2.request({"op": "register_worker", "recovery": 0})
        assert resp2["rank"] == 0
        assert sched.worker_ranks == 1

        for c in (srv, wrk):
            c.request({"op": "finalize"})
            c.close()
        wrk2.close()
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        os.environ.pop("DMLC_PS_ROOT_URI", None)
        os.environ.pop("DMLC_PS_ROOT_PORT", None)


_STALL_WORKER = r"""
import os, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd

kv = mx.kv.create("dist_sync")
kv.init("k", nd.zeros((4,)))
open(sys.argv[1], "w").write("ready")
# keep pushing/pulling until the (stopped) server stops answering
try:
    for i in range(10000):
        kv.push("k", nd.ones((4,)))
        out = nd.zeros((4,))
        kv.pull("k", out=out)
except Exception as e:
    print("worker saw failure: %r" % e, flush=True)
    sys.exit(42)
sys.exit(0)
"""


def test_kill_server_mid_push_raises_within_timeout(tmp_path):
    """SIGSTOP the server mid-run (socket stays open — the true hang
    case): the worker must exit with our failure code within the request
    timeout instead of hanging forever."""
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_SERVER": "1",
        "DMLC_NUM_WORKER": "1",
        "MXNET_PS_REQUEST_TIMEOUT": "3",
    })
    env.pop("XLA_FLAGS", None)

    def spawn(role, argv):
        e = dict(env)
        e["DMLC_ROLE"] = role
        return subprocess.Popen(argv, env=e, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    node = [sys.executable, "-c",
            "import mxnet_tpu.kvstore_server as s; s.init()"]
    ready = str(tmp_path / "ready")
    wscript = str(tmp_path / "worker.py")
    with open(wscript, "w") as f:
        f.write(_STALL_WORKER)

    sched = spawn("scheduler", node)
    server = spawn("server", node)
    worker = spawn("worker", [sys.executable, wscript, ready])
    try:
        deadline = time.time() + 60
        while not os.path.exists(ready):
            assert time.time() < deadline, "cluster never came up"
            assert worker.poll() is None, worker.communicate()[0]
            time.sleep(0.1)
        os.kill(server.pid, signal.SIGSTOP)  # hung, not closed
        t0 = time.time()
        try:
            rc = worker.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pytest.fail("worker hung on a stopped server")
        elapsed = time.time() - t0
        out = worker.communicate()[0].decode()
        assert rc == 42, out
        assert "failure" in out
        assert elapsed < 25
    finally:
        for p in (worker, sched):
            if p.poll() is None:
                p.kill()
        try:
            os.kill(server.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        server.wait()
        sched.wait()
        worker.wait()
