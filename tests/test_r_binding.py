"""R frontend training slice (VERDICT r4 missing #1): R-package/ builds
`src/mxnet_r.c` with R CMD SHLIB against the native C ABI and trains an
MLP to >0.9 val accuracy with every float minted in R
(tests/train_test.R — the R analogue of perl's t/train.t).

Skips when no R toolchain exists: the round-5 build image ships no R
interpreter (R-package/README.md documents the ADR), so on such boxes
the runnable-non-python-frontend proof remains the perl suite.
"""
import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
RPKG = os.path.join(ROOT, "R-package")


def test_r_glue_compiles_against_stub_headers():
    """The .Call glue must stay a valid C translation unit even where R
    is absent: src/r_stub_headers declares exactly the R-API subset the
    glue uses, so type/syntax breakage is caught in this image too."""
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    r = subprocess.run(
        ["gcc", "-fsyntax-only", "-Wall",
         "-I", os.path.join(RPKG, "src", "r_stub_headers"),
         "-I", os.path.join(ROOT, "include"),
         os.path.join(RPKG, "src", "mxnet_r.c")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]


@pytest.mark.slow
def test_r_binding_end_to_end(tmp_path):
    if shutil.which("R") is None or shutil.which("Rscript") is None:
        pytest.skip("R toolchain absent (documented: R-package/README.md "
                    "environment note)")
    from cabi_common import ensure_lib

    ensure_lib()
    import mxnet_tpu as mx

    # un-trained MLP symbol fixture (same net as the perl train slice)
    data = mx.sym.Variable("data")
    h1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    a1 = mx.sym.Activation(h1, act_type="relu")
    h2 = mx.sym.FullyConnected(a1, name="fc2", num_hidden=10)
    train_sym = mx.sym.SoftmaxOutput(h2, name="softmax")
    fix = tmp_path / "fixture"
    fix.mkdir()
    with open(fix / "train-symbol.json", "w") as f:
        f.write(train_sym.tojson())

    build = tmp_path / "r-build"
    shutil.copytree(RPKG, str(build))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=ROOT,
               MXTPU_ROOT=ROOT,
               MXTPU_RPKG=str(build),
               MXTPU_SHIM=str(build / "src" / "mxnet_r.so"),
               MXTPU_FIXTURE_DIR=str(fix),
               PKG_CPPFLAGS="-I%s" % os.path.join(ROOT, "include"),
               PKG_LIBS="-L%s -lmxnet_tpu -Wl,-rpath,%s" % (
                   os.path.join(ROOT, "native"),
                   os.path.join(ROOT, "native")))
    r = subprocess.run(["R", "CMD", "SHLIB", "mxnet_r.c", "-o",
                        "mxnet_r.so"], cwd=str(build / "src"), env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["Rscript", str(build / "tests" / "train_test.R")],
                       cwd=str(tmp_path), env=env, capture_output=True,
                       text=True, timeout=1800)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "R_TRAIN_OK" in r.stdout, r.stdout[-2000:]
