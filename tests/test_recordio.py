"""Native RecordIO + ImageRecordIter tests, modeled on the reference's
tests/python/unittest/test_recordio.py and the ImageRecordIter cases of
test_io.py."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


# ---------------------------------------------------------------------------
# raw record container
# ---------------------------------------------------------------------------
def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"", b"x" * 1000, b"odd123"]
    for p in payloads:
        w.write(p)
    w.close()

    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    r.close()
    assert got == payloads


def test_recordio_magic_escape(tmp_path):
    """Payloads containing the wire magic must round-trip (dmlc recordio
    split/reassemble protocol)."""
    magic = struct.pack("<I", 0xced7230a)
    payloads = [magic, b"ab" + magic + b"cd", magic * 3, b"z" * 7 + magic]
    path = str(tmp_path / "m.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expect in payloads:
        assert r.read() == expect
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    rec, idx = str(tmp_path / "b.rec"), str(tmp_path / "b.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(20):
        w.write_idx(i, b"record-%d" % i)
    w.close()

    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.keys == list(range(20))
    # random access, out of order
    for i in [7, 0, 19, 3, 3]:
        assert r.read_idx(i) == b"record-%d" % i
    r.close()


def test_irheader_pack_unpack():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 3.0 and h2.id == 42
    # multi-label
    hm = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 7, 0)
    s = recordio.pack(hm, b"img")
    h3, payload = recordio.unpack(s)
    assert payload == b"img"
    np.testing.assert_array_equal(h3.label, [1.0, 2.0, 3.0])
    assert h3.flag == 3


def test_pack_img_roundtrip():
    cv2 = pytest.importorskip("cv2")
    yy, xx = np.mgrid[0:32, 0:24]
    img = np.stack([yy * 8, xx * 10, (yy + xx) * 4], axis=-1).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img, quality=95)
    header, decoded = recordio.unpack_img(s)
    assert header.label == 1.0
    assert decoded.shape == (32, 24, 3)
    # JPEG is lossy; mean error should still be small
    assert np.abs(decoded.astype(int) - img.astype(int)).mean() < 12


# ---------------------------------------------------------------------------
# the native image pipeline
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def image_rec(tmp_path_factory):
    """A tiny 3-class jpeg dataset packed with im2rec's code path."""
    cv2 = pytest.importorskip("cv2")
    root = tmp_path_factory.mktemp("imgs")
    prefix = str(root / "data")
    n_per_class, size = 8, 40
    rng = np.random.RandomState(1)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    idx = 0
    labels = {}
    for cls in range(3):
        base = np.full((size, size, 3), cls * 80 + 40, np.uint8)
        for _ in range(n_per_class):
            img = (base + rng.randint(0, 20, base.shape)).astype(np.uint8)
            rec.write_idx(idx, recordio.pack_img(
                recordio.IRHeader(0, float(cls), idx, 0), img))
            labels[idx] = cls
            idx += 1
    rec.close()
    return prefix, labels


def test_image_record_iter(image_rec):
    prefix, labels = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=6,
        shuffle=False, preprocess_threads=2, round_batch=False)
    assert it.num_records == 24
    batches = list(it)
    assert len(batches) == 4  # 24 / 6
    b = batches[0]
    assert b.data[0].shape == (6, 3, 32, 32)
    assert b.label[0].shape == (6,)
    # unshuffled: first six labels are class 0
    np.testing.assert_array_equal(b.label[0].asnumpy(), [0] * 6)
    # pixel content: class-0 images have mean ~40-60 before normalize
    mean_px = float(b.data[0].asnumpy().mean())
    assert 30 < mean_px < 70

    # reset replays the epoch
    it.reset()
    again = next(it)
    np.testing.assert_allclose(again.data[0].asnumpy(),
                               b.data[0].asnumpy(), rtol=1e-6)


def test_image_record_iter_with_idx(image_rec):
    """path_imgidx loads offsets from the .idx sidecar (no full .rec scan)
    and yields the identical stream."""
    prefix, labels = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
        data_shape=(3, 32, 32), batch_size=6,
        shuffle=False, preprocess_threads=2, round_batch=False)
    assert it.num_records == 24
    ref = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=6,
        shuffle=False, preprocess_threads=2, round_batch=False)
    for b, r in zip(it, ref):
        np.testing.assert_allclose(b.data[0].asnumpy(), r.data[0].asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_array_equal(b.label[0].asnumpy(), r.label[0].asnumpy())


def test_image_record_iter_grayscale(image_rec):
    """c=1 data_shape converts color JPEGs via BT.601 luma, not channel R."""
    prefix, labels = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(1, 32, 32), batch_size=6,
        shuffle=False, preprocess_threads=2, round_batch=False)
    b = next(it)
    assert b.data[0].shape == (6, 1, 32, 32)
    # class-0 grey-ish images: luma ≈ channel mean ≈ 40-60
    mean_px = float(b.data[0].asnumpy().mean())
    assert 30 < mean_px < 70


def test_image_record_iter_shuffle_and_augment(image_rec):
    prefix, labels = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=8,
        shuffle=True, rand_mirror=True, rand_crop=True, resize=36,
        mean_r=128.0, mean_g=128.0, mean_b=128.0,
        std_r=64.0, std_g=64.0, std_b=64.0,
        preprocess_threads=3, seed=5)
    seen = []
    for batch in it:
        seen.extend(batch.label[0].asnumpy().astype(int).tolist())
    assert len(seen) == 24
    # shuffled order interleaves classes
    assert seen[:8] != [0] * 8
    # all records seen exactly once per epoch
    assert sorted(seen) == sorted(labels.values())

    # normalization applied: class means map near (value-128)/64
    it.reset()
    batch = next(it)
    data = batch.data[0].asnumpy()
    assert -3.0 < data.mean() < 3.0


def test_image_record_iter_round_batch(image_rec):
    prefix, _ = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=10,
        shuffle=False, round_batch=True, preprocess_threads=2)
    batches = list(it)
    # 24 records, batch 10 → 3 batches with wrap-around padding
    assert len(batches) == 3


def test_image_record_iter_provide(image_rec):
    prefix, _ = image_rec
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 32, 32), batch_size=4)
    d = it.provide_data[0]
    assert d.shape == (4, 3, 32, 32)
    assert it.provide_label[0].shape == (4,)


def test_pack_numpy_scalar_label():
    """np.float32 labels must take the scalar wire path (flag=0)."""
    s = recordio.pack(recordio.IRHeader(0, np.float32(3.0), 5, 0), b"p")
    h, payload = recordio.unpack(s)
    assert h.flag == 0 and float(h.label) == 3.0 and payload == b"p"


def test_pickle_reader_refuse_open_writer(tmp_path):
    import pickle

    path = str(tmp_path / "p.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"keep-me")
    with pytest.raises(Exception):
        pickle.dumps(w)  # open writer must refuse (would truncate)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.read() == b"keep-me"
    # the original file was never truncated
    assert recordio.MXRecordIO(path, "r").read() == b"keep-me"


def test_image_iter_partial_tail_pad(image_rec):
    """24 records, batch 10: the tail batch is emitted with pad reported."""
    prefix, labels = image_rec
    for round_batch in (True, False):
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 32, 32),
            batch_size=10, shuffle=False, round_batch=round_batch,
            preprocess_threads=2)
        batches = list(it)
        assert len(batches) == 3
        assert [b.pad for b in batches] == [0, 0, 6]
        seen = []
        for b in batches[:-1]:
            seen.extend(b.label[0].asnumpy().astype(int).tolist())
        last = batches[-1].label[0].asnumpy().astype(int).tolist()
        seen.extend(last[:4])  # ignore pad
        assert sorted(seen) == sorted(labels.values())


def test_image_iter_small_dataset_pads(tmp_path):
    """Datasets smaller than one batch still yield a (padded) batch."""
    cv2 = pytest.importorskip("cv2")
    prefix = str(tmp_path / "small")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(3):
        img = np.full((16, 16, 3), 50 * (i + 1), np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=8,
                               shuffle=False, preprocess_threads=1)
    batches = list(it)
    assert len(batches) == 1
    assert batches[0].pad == 5
    np.testing.assert_array_equal(
        batches[0].label[0].asnumpy()[:3].astype(int), [0, 1, 2])


def test_image_iter_grayscale_raw(tmp_path):
    """c=1 raw payloads read with single-channel stride (no OOB)."""
    prefix = str(tmp_path / "gray")
    rec = recordio.MXRecordIO(prefix + ".rec", "w")
    for i in range(4):
        raw = np.full((6, 6, 1), 10 * (i + 1), np.uint8)
        rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                raw.tobytes()))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(1, 6, 6), batch_size=4,
                               shuffle=False, preprocess_threads=1)
    b = next(it)
    data = b.data[0].asnumpy()
    assert data.shape == (4, 1, 6, 6)
    for i in range(4):
        np.testing.assert_array_equal(data[i], np.full((1, 6, 6),
                                                       10.0 * (i + 1)))
