"""The BASELINE.md north star, demonstrated literally: the reference
repo's example scripts run **byte-identical** (straight out of
/root/reference) against this framework through the ``compat/mxnet``
import shim.

Covered: example/image-classification/{train_mnist,train_cifar10,
train_imagenet,benchmark_score}.py and example/gluon/
image_classification.py.  Data comes from pre-seeded synthetic files
(offline environment) — the scripts' own download helpers short-circuit
on existing files; CLI flags are the scripts' documented interface.
"""
import gzip
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

REFERENCE = "/root/reference"
IC_DIR = os.path.join(REFERENCE, "example", "image-classification")
ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

pytestmark = pytest.mark.skipif(
    not os.path.isdir(IC_DIR), reason="reference tree not present")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.join(ROOT, "compat"), ROOT,
         env.get("PYTHONPATH", "")])
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)  # single-device is fine for the scripts
    return env


def _write_mnist(data_dir):
    rng = np.random.RandomState(0)

    def write(prefix, n):
        labels = (np.arange(n) % 10).astype(np.uint8)
        imgs = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(labels):
            img = rng.randint(0, 30, (28, 28))
            img[c:c + 10, c:c + 10] += 180
            imgs[i] = np.clip(img, 0, 255)
        with gzip.open(prefix % "labels-idx1", "wb") as f:
            f.write(struct.pack(">II", 2049, n) + labels.tobytes())
        with gzip.open(prefix % "images-idx3", "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())

    write(os.path.join(data_dir, "train-%s-ubyte.gz"), 2000)
    write(os.path.join(data_dir, "t10k-%s-ubyte.gz"), 1000)


def _write_cifar_rec(data_dir):
    from mxnet_tpu import recordio

    rng = np.random.RandomState(1)
    for name, n in (("cifar10_train.rec", 512), ("cifar10_val.rec", 256)):
        w = recordio.MXRecordIO(os.path.join(data_dir, name), "w")
        for i in range(n):
            c = i % 10
            img = rng.randint(0, 60, (32, 32, 3)).astype(np.uint8)
            img[:, :, c % 3] = np.clip(
                img[:, :, c % 3].astype(int) + 40 + 15 * c, 0, 255)
            hdr = recordio.IRHeader(0, float(c), i, 0)
            w.write(recordio.pack_img(hdr, img, quality=95))
        w.close()


def _run(script, args, cwd, timeout=900):
    proc = subprocess.run([sys.executable, script] + args, cwd=cwd,
                          env=_env(), capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-4000:]
    return proc.stdout + proc.stderr


def _val_accuracies(log):
    out = []
    for line in log.splitlines():
        if "Validation-accuracy=" in line:
            out.append(float(line.rsplit("=", 1)[1]))
    return out


@pytest.mark.slow
def test_reference_train_mnist_unmodified(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    _write_mnist(str(data))
    log = _run(os.path.join(IC_DIR, "train_mnist.py"),
               ["--num-epochs", "2", "--disp-batches", "10"],
               cwd=str(tmp_path))
    accs = _val_accuracies(log)
    assert accs and accs[-1] > 0.95, log[-2000:]


@pytest.mark.slow
def test_reference_train_cifar10_unmodified(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    _write_cifar_rec(str(data))
    log = _run(os.path.join(IC_DIR, "train_cifar10.py"),
               ["--network", "lenet", "--num-epochs", "2",
                "--batch-size", "64", "--disp-batches", "4"],
               cwd=str(tmp_path))
    accs = _val_accuracies(log)
    assert accs and accs[-1] > 0.5, log[-2000:]


@pytest.mark.slow
def test_reference_train_imagenet_benchmark_mode(tmp_path):
    log = _run(os.path.join(IC_DIR, "train_imagenet.py"),
               ["--benchmark", "1", "--network", "lenet",
                "--image-shape", "3,28,28", "--num-classes", "10",
                "--num-examples", "6400", "--num-epochs", "1",
                "--batch-size", "32", "--disp-batches", "100"],
               cwd=str(tmp_path))
    assert "Train-accuracy" in log, log[-2000:]


@pytest.mark.slow
def test_reference_benchmark_score_unmodified(tmp_path):
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import mxnet as mx\n"
        "import benchmark_score\n"
        "s = benchmark_score.score(network='resnet-18', dev=mx.cpu(),"
        " batch_size=1, num_batches=2)\n"
        "assert s > 0\n"
        "print('SCORE_OK', s)\n" % IC_DIR)
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                          env=_env(), capture_output=True, text=True,
                          timeout=900)
    assert proc.returncode == 0 and "SCORE_OK" in proc.stdout, \
        (proc.stdout + proc.stderr)[-4000:]


@pytest.mark.slow
def test_reference_gluon_image_classification_unmodified(tmp_path):
    script = os.path.join(REFERENCE, "example", "gluon",
                          "image_classification.py")
    log = _run(script,
               ["--dataset", "dummy", "--model", "resnet18_v1",
                "--epochs", "1", "--mode", "hybrid",
                "--batch-size", "2", "--log-interval", "50"],
               cwd=str(tmp_path), timeout=1500)
    assert "validation: accuracy=" in log, log[-2000:]


def test_reference_weighted_logistic_regression_unmodified(tmp_path):
    """example/numpy-ops: the CustomOp bridge driven by the reference's
    own script — symbol Custom with an auto-created label variable,
    simple_bind, forward and exact backward."""
    script = os.path.join(REFERENCE, "example", "numpy-ops",
                          "weighted_logistic_regression.py")
    log = _run(script, [], cwd=str(tmp_path))
    assert "Weighted Logistic Regression gradients:" in log
    # the weighted negative-class gradient is exactly 0.1x the plain one
    assert "0.01462117" in log and "0.14621173" in log, log[-2000:]


def test_reference_gluon_lr_manipulation_unmodified(tmp_path):
    """example/gluon/learning_rate_manipulation.py: Trainer lr getters/
    setters + NDArrayIter, converging to the synthetic ground truth."""
    script = os.path.join(REFERENCE, "example", "gluon",
                          "learning_rate_manipulation.py")
    log = _run(script, [], cwd=str(tmp_path))
    assert "Learning rate: 0.1" in log
    assert "0.0729" in log  # 0.1 * 0.9^3 after per-epoch decay
    # regression weights converge near (2, -3.4), bias near 4.2
    assert "dense0_bias 4.1" in log or "dense0_bias 4.2" in log, \
        log[-2000:]


@pytest.mark.slow
def test_reference_gluon_mnist_unmodified(tmp_path):
    """example/gluon/mnist.py: gluon.data.vision.MNIST + DataLoader +
    Trainer, byte-identical."""
    data = tmp_path / "data"
    data.mkdir()
    _write_mnist(str(data))
    script = os.path.join(REFERENCE, "example", "gluon", "mnist.py")
    log = _run(script, ["--epochs", "1"], cwd=str(tmp_path))
    assert "Validation: accuracy=" in log, log[-2000:]
    acc = float(log.rsplit("Validation: accuracy=", 1)[1].split()[0])
    assert acc > 0.9, log[-2000:]


# ---------------------------------------------------------------------------
# BASELINE configs 3-5: lstm_bucketing, model-parallel lstm, SSD
# ---------------------------------------------------------------------------
def _write_ptb_like(data_dir, names=("ptb.train.txt", "ptb.test.txt"),
                    sizes=(400, 120)):
    import random as _random

    rng = _random.Random(0)
    words = ["the", "a", "cat", "dog", "runs", "jumps", "over", "lazy",
             "quick", "brown", "fox", "house", "tree", "river", "stone",
             "bird", "sings", "loud", "soft", "wind"]
    for name, n in zip(names, sizes):
        with open(os.path.join(data_dir, name), "w") as f:
            for _ in range(n):
                ln = rng.randint(5, 45)
                f.write(" ".join(rng.choice(words) for _ in range(ln))
                        + " \n")


@pytest.mark.slow
def test_reference_lstm_bucketing_unmodified(tmp_path):
    """BASELINE config 3: example/rnn/bucketing/lstm_bucketing.py runs
    byte-identical on synthetic PTB-format text."""
    data = tmp_path / "data"
    data.mkdir()
    _write_ptb_like(str(data))
    log = _run(os.path.join(REFERENCE, "example", "rnn", "bucketing",
                            "lstm_bucketing.py"),
               ["--num-epochs", "2", "--num-layers", "1", "--num-hidden",
                "32", "--num-embed", "16", "--batch-size", "16",
                "--disp-batches", "5"],
               cwd=str(tmp_path))
    perps = [float(l.rsplit("=", 1)[1]) for l in log.splitlines()
             if "Validation-perplexity=" in l]
    assert len(perps) == 2, log[-2000:]
    assert all(np.isfinite(p) for p in perps), perps
    assert perps[-1] < perps[0], perps  # it learns


@pytest.mark.slow
def test_reference_model_parallel_lstm(tmp_path):
    """BASELINE config 5: the reference model-parallel LSTM library
    (example/model-parallel/lstm/lstm.py) imported byte-identical,
    trained with ctx_group placement over distinct virtual devices.
    (Its driver's bucket_io dependency is python2-only, so the runner
    supplies the tiny data iterator; all modeling/executor/training
    code is the reference's own — see tests/mp_lstm_runner.py.)"""
    env = _env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tests", "mp_lstm_runner.py")],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-4000:]
    assert "MP_LSTM_OK" in proc.stdout


def _write_ssd_rec(path, n, seed, classes=3):
    """Synthetic VOC-format detection rec: one bright block per dark
    image, header label [2, 6, cls, x1, y1, x2, y2, 0]."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        cls = i % classes
        img = rng.randint(0, 60, (160, 160, 3), dtype=np.uint8)
        x1, y1 = rng.uniform(0.1, 0.35, 2)
        x2, y2 = min(0.95, x1 + 0.5), min(0.95, y1 + 0.5)
        px = (np.array([x1, y1, x2, y2]) * 160).astype(int)
        if classes == 1:
            img[px[1]:px[3], px[0]:px[2], :] = 230
        else:
            img[px[1]:px[3], px[0]:px[2], cls] = 220
        lab = [2, 6, float(cls), x1, y1, x2, y2, 0.0]
        w.write(recordio.pack_img(
            recordio.IRHeader(0, np.array(lab, np.float32), i, 0),
            img, quality=95))
    w.close()


_SSD_ALIAS_PREAMBLE = (
    "import collections, collections.abc as _abc\n"
    "for _n in ('Mapping','MutableMapping','Sequence','Iterable'):\n"
    "    setattr(collections, _n, getattr(_abc, _n))\n"
    "import sys, runpy\n")


@pytest.mark.slow
def test_reference_ssd_train_unmodified(tmp_path):
    """BASELINE config 4, multi-class CE-dip proof (as r3):
    example/ssd/train.py byte-identical at resnet50@256 on a synthetic
    3-class VOC-format rec.  The launcher aliases collections.Mapping
    -> collections.abc.Mapping first (stdlib name removed in py3.10;
    the reference's config/utils.py predates that) — no reference file
    is modified.  The mAP-level proof lives in
    test_reference_ssd_evaluate_map (a from-scratch resnet50-SSD needs
    a longer budget to emit confident detections; measured sweep:
    48-160 updates at 256px leave every anchor background)."""
    rec = str(tmp_path / "train.rec")
    _write_ssd_rec(rec, 24, seed=0)
    (tmp_path / "model").mkdir()
    end_epoch = 3
    code = (
        _SSD_ALIAS_PREAMBLE +
        "sys.path.insert(0, %r)\n"
        "sys.argv = ['train.py', '--train-path', %r, '--val-path', '',\n"
        "  '--pretrained', '', '--network', 'resnet50', '--data-shape',\n"
        "  '256', '--batch-size', '4', '--end-epoch', '%d', '--frequent',\n"
        "  '10', '--num-class', '3', '--class-names', 'a, b, c',\n"
        "  '--num-example', '24', '--label-width', '24', '--prefix', %r,\n"
        "  '--lr', '0.002', '--log', %r]\n"
        "runpy.run_path(%r, run_name='__main__')\n"
        % (os.path.join(REFERENCE, "example", "ssd"), rec, end_epoch,
           str(tmp_path / "model" / "ssd"), str(tmp_path / "train.log"),
           os.path.join(REFERENCE, "example", "ssd", "train.py")))
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                          env=_env(), capture_output=True, text=True,
                          timeout=2400)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    ces = [float(l.rsplit("=", 1)[1]) for l in out.splitlines()
           if "Train-CrossEntropy=" in l]
    assert len(ces) == end_epoch and all(np.isfinite(c) for c in ces), \
        out[-2000:]
    # 6 batches/epoch with random augmentation: the CE comparison is a
    # noisy no-divergence check (10% slack); the learning-level proof
    # is test_reference_ssd_evaluate_map's mAP
    assert min(ces[1:]) < ces[0] * 1.1, ces
    assert os.path.exists(str(tmp_path / "model" /
                              ("ssd_resnet50_256-%04d.params"
                               % end_epoch)))


@pytest.mark.slow
def test_reference_ssd_evaluate_map(tmp_path):
    """The reference's OWN evaluation path end-to-end (VERDICT r3 item
    9, held-out split per VERDICT r4 item 8): train.py byte-identical
    long enough for real detections (single bright class, 128px, lr
    0.002 with the script's own step-decay schedule — sweep-validated:
    constant lr either leaves every anchor background by 40 epochs or
    diverges to NaN by 80), then evaluate.py byte-identical —
    DetRecordIter, NMS decode, VOC07MApMetric — TWICE: on the train rec
    (pipeline-discriminates bar, as r4) and on a FRESH same-distribution
    rec the detector never saw (generalization bar).  Both mAPs are
    printed for the record."""
    import re

    rec = str(tmp_path / "train.rec")
    _write_ssd_rec(rec, 32, seed=0, classes=1)
    heldout = str(tmp_path / "heldout.rec")
    _write_ssd_rec(heldout, 32, seed=1, classes=1)
    (tmp_path / "model").mkdir()
    end_epoch = 60
    code = (
        _SSD_ALIAS_PREAMBLE +
        "sys.path.insert(0, %r)\n"
        "sys.argv = ['train.py', '--train-path', %r, '--val-path', '',\n"
        "  '--pretrained', '', '--network', 'resnet50', '--data-shape',\n"
        "  '128', '--batch-size', '8', '--end-epoch', '%d',\n"
        "  '--frequent', '40', '--num-class', '1', '--class-names',\n"
        "  'a', '--num-example', '32', '--label-width', '24',\n"
        "  '--prefix', %r, '--lr', '0.002', '--lr-steps', '20,35,50',\n"
        "  '--lr-factor', '0.4', '--log', %r]\n"
        "runpy.run_path(%r, run_name='__main__')\n"
        % (os.path.join(REFERENCE, "example", "ssd"), rec, end_epoch,
           str(tmp_path / "model" / "ssd"), str(tmp_path / "train.log"),
           os.path.join(REFERENCE, "example", "ssd", "train.py")))
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                          env=_env(), capture_output=True, text=True,
                          timeout=3300)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]

    def _evaluate(rec_path):
        eval_code = (
            _SSD_ALIAS_PREAMBLE +
            "sys.path.insert(0, %r)\n"
            "sys.argv = ['evaluate.py', '--cpu', '--rec-path', %r,\n"
            "  '--network', 'resnet50', '--data-shape', '128',\n"
            "  '--batch-size', '8', '--num-class', '1', '--class-names',\n"
            "  'a', '--prefix', %r, '--epoch', '%d']\n"
            "runpy.run_path(%r, run_name='__main__')\n"
            % (os.path.join(REFERENCE, "example", "ssd"), rec_path,
               str(tmp_path / "model" / "ssd_resnet50"), end_epoch,
               os.path.join(REFERENCE, "example", "ssd", "evaluate.py")))
        proc = subprocess.run([sys.executable, "-c", eval_code],
                              cwd=str(tmp_path), env=_env(),
                              capture_output=True, text=True, timeout=900)
        eout = proc.stdout + proc.stderr
        assert proc.returncode == 0, eout[-4000:]
        m = re.search(r"mAP: ([\d.naife]+)", eout)
        assert m, eout[-2000:]
        map_val = float(m.group(1))
        assert np.isfinite(map_val), eout[-1000:]
        return map_val, eout

    map_train, train_eval_log = _evaluate(rec)
    map_heldout, _ = _evaluate(heldout)
    print("SSD_MAP train=%.4f heldout=%.4f" % (map_train, map_heldout))
    # chance for random boxes at 0.5 IoU is ~0; the VOC07 machinery must
    # see real true positives BOTH on the train set (pipeline
    # discriminates) and on images the detector never saw (generalizes)
    assert map_train > 0.02, (map_train, train_eval_log[-1500:])
    assert map_heldout > 0.02, (map_train, map_heldout)


@pytest.mark.slow
def test_reference_train_imagenet_rec_data_path(tmp_path):
    """train_imagenet.py on its REAL rec-file data path (not benchmark
    mode): ImageRecordIter feeds training + validation through the
    native pipeline (VERDICT r2 weak #4)."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    for name, n in (("train", 192), ("val", 64)):
        w = recordio.MXIndexedRecordIO(str(tmp_path / (name + ".idx")),
                                       str(tmp_path / (name + ".rec")),
                                       "w")
        for i in range(n):
            c = i % 10
            img = rng.randint(0, 60, (140, 140, 3), dtype=np.uint8)
            img[:, :, c % 3] = np.clip(img[:, :, c % 3] + 60 + 12 * c,
                                       0, 255)
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(c), i, 0), img, quality=90))
        w.close()
    log = _run(os.path.join(IC_DIR, "train_imagenet.py"),
               ["--data-train", str(tmp_path / "train.rec"),
                "--data-train-idx", str(tmp_path / "train.idx"),
                "--data-val", str(tmp_path / "val.rec"),
                "--data-val-idx", str(tmp_path / "val.idx"),
                "--network", "lenet", "--image-shape", "3,64,64",
                "--num-classes", "10", "--num-examples", "192",
                "--batch-size", "32", "--num-epochs", "6", "--lr",
                "0.05", "--disp-batches", "4", "--data-nthreads", "2"],
               cwd=str(tmp_path))
    accs = _val_accuracies(log)
    assert len(accs) == 6 and accs[-1] > 0.5, (accs, log[-1500:])
