"""The BASELINE.md north star, demonstrated literally: the reference
repo's example scripts run **byte-identical** (straight out of
/root/reference) against this framework through the ``compat/mxnet``
import shim.

Covered: example/image-classification/{train_mnist,train_cifar10,
train_imagenet,benchmark_score}.py and example/gluon/
image_classification.py.  Data comes from pre-seeded synthetic files
(offline environment) — the scripts' own download helpers short-circuit
on existing files; CLI flags are the scripts' documented interface.
"""
import gzip
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

REFERENCE = "/root/reference"
IC_DIR = os.path.join(REFERENCE, "example", "image-classification")
ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

pytestmark = pytest.mark.skipif(
    not os.path.isdir(IC_DIR), reason="reference tree not present")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.join(ROOT, "compat"), ROOT,
         env.get("PYTHONPATH", "")])
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)  # single-device is fine for the scripts
    return env


def _write_mnist(data_dir):
    rng = np.random.RandomState(0)

    def write(prefix, n):
        labels = (np.arange(n) % 10).astype(np.uint8)
        imgs = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(labels):
            img = rng.randint(0, 30, (28, 28))
            img[c:c + 10, c:c + 10] += 180
            imgs[i] = np.clip(img, 0, 255)
        with gzip.open(prefix % "labels-idx1", "wb") as f:
            f.write(struct.pack(">II", 2049, n) + labels.tobytes())
        with gzip.open(prefix % "images-idx3", "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28) + imgs.tobytes())

    write(os.path.join(data_dir, "train-%s-ubyte.gz"), 2000)
    write(os.path.join(data_dir, "t10k-%s-ubyte.gz"), 1000)


def _write_cifar_rec(data_dir):
    from mxnet_tpu import recordio

    rng = np.random.RandomState(1)
    for name, n in (("cifar10_train.rec", 512), ("cifar10_val.rec", 256)):
        w = recordio.MXRecordIO(os.path.join(data_dir, name), "w")
        for i in range(n):
            c = i % 10
            img = rng.randint(0, 60, (32, 32, 3)).astype(np.uint8)
            img[:, :, c % 3] = np.clip(
                img[:, :, c % 3].astype(int) + 40 + 15 * c, 0, 255)
            hdr = recordio.IRHeader(0, float(c), i, 0)
            w.write(recordio.pack_img(hdr, img, quality=95))
        w.close()


def _run(script, args, cwd, timeout=900):
    proc = subprocess.run([sys.executable, script] + args, cwd=cwd,
                          env=_env(), capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-4000:]
    return proc.stdout + proc.stderr


def _val_accuracies(log):
    out = []
    for line in log.splitlines():
        if "Validation-accuracy=" in line:
            out.append(float(line.rsplit("=", 1)[1]))
    return out


@pytest.mark.slow
def test_reference_train_mnist_unmodified(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    _write_mnist(str(data))
    log = _run(os.path.join(IC_DIR, "train_mnist.py"),
               ["--num-epochs", "2", "--disp-batches", "10"],
               cwd=str(tmp_path))
    accs = _val_accuracies(log)
    assert accs and accs[-1] > 0.95, log[-2000:]


@pytest.mark.slow
def test_reference_train_cifar10_unmodified(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    _write_cifar_rec(str(data))
    log = _run(os.path.join(IC_DIR, "train_cifar10.py"),
               ["--network", "lenet", "--num-epochs", "2",
                "--batch-size", "64", "--disp-batches", "4"],
               cwd=str(tmp_path))
    accs = _val_accuracies(log)
    assert accs and accs[-1] > 0.5, log[-2000:]


@pytest.mark.slow
def test_reference_train_imagenet_benchmark_mode(tmp_path):
    log = _run(os.path.join(IC_DIR, "train_imagenet.py"),
               ["--benchmark", "1", "--network", "lenet",
                "--image-shape", "3,28,28", "--num-classes", "10",
                "--num-examples", "6400", "--num-epochs", "1",
                "--batch-size", "32", "--disp-batches", "100"],
               cwd=str(tmp_path))
    assert "Train-accuracy" in log, log[-2000:]


@pytest.mark.slow
def test_reference_benchmark_score_unmodified(tmp_path):
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import mxnet as mx\n"
        "import benchmark_score\n"
        "s = benchmark_score.score(network='resnet-18', dev=mx.cpu(),"
        " batch_size=1, num_batches=2)\n"
        "assert s > 0\n"
        "print('SCORE_OK', s)\n" % IC_DIR)
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                          env=_env(), capture_output=True, text=True,
                          timeout=900)
    assert proc.returncode == 0 and "SCORE_OK" in proc.stdout, \
        (proc.stdout + proc.stderr)[-4000:]


@pytest.mark.slow
def test_reference_gluon_image_classification_unmodified(tmp_path):
    script = os.path.join(REFERENCE, "example", "gluon",
                          "image_classification.py")
    log = _run(script,
               ["--dataset", "dummy", "--model", "resnet18_v1",
                "--epochs", "1", "--mode", "hybrid",
                "--batch-size", "2", "--log-interval", "50"],
               cwd=str(tmp_path), timeout=1500)
    assert "validation: accuracy=" in log, log[-2000:]


def test_reference_weighted_logistic_regression_unmodified(tmp_path):
    """example/numpy-ops: the CustomOp bridge driven by the reference's
    own script — symbol Custom with an auto-created label variable,
    simple_bind, forward and exact backward."""
    script = os.path.join(REFERENCE, "example", "numpy-ops",
                          "weighted_logistic_regression.py")
    log = _run(script, [], cwd=str(tmp_path))
    assert "Weighted Logistic Regression gradients:" in log
    # the weighted negative-class gradient is exactly 0.1x the plain one
    assert "0.01462117" in log and "0.14621173" in log, log[-2000:]


def test_reference_gluon_lr_manipulation_unmodified(tmp_path):
    """example/gluon/learning_rate_manipulation.py: Trainer lr getters/
    setters + NDArrayIter, converging to the synthetic ground truth."""
    script = os.path.join(REFERENCE, "example", "gluon",
                          "learning_rate_manipulation.py")
    log = _run(script, [], cwd=str(tmp_path))
    assert "Learning rate: 0.1" in log
    assert "0.0729" in log  # 0.1 * 0.9^3 after per-epoch decay
    # regression weights converge near (2, -3.4), bias near 4.2
    assert "dense0_bias 4.1" in log or "dense0_bias 4.2" in log, \
        log[-2000:]


@pytest.mark.slow
def test_reference_gluon_mnist_unmodified(tmp_path):
    """example/gluon/mnist.py: gluon.data.vision.MNIST + DataLoader +
    Trainer, byte-identical."""
    data = tmp_path / "data"
    data.mkdir()
    _write_mnist(str(data))
    script = os.path.join(REFERENCE, "example", "gluon", "mnist.py")
    log = _run(script, ["--epochs", "1"], cwd=str(tmp_path))
    assert "Validation: accuracy=" in log, log[-2000:]
    acc = float(log.rsplit("Validation: accuracy=", 1)[1].split()[0])
    assert acc > 0.9, log[-2000:]
