"""MXNET_BACKWARD_DO_MIRROR — the remat/mirror memory knob.

Reference contract: src/executor/graph_executor.cc:249 (InitFullGraph
mirror augmentation) recomputes activation/BN class nodes in backward to
trade compute for memory; example/image-classification/README.md:370-373
documents the batch-doubling trade.  Here the knob wraps the traced
program in jax.checkpoint with a conv/matmul-saveable policy (remat.py).

Tested: env parsing; gradient equivalence with the knob on vs off on
BOTH the gluon/CachedOp path and the symbolic executor path; and that
the policy genuinely drops activation-sized residuals (the memory
mechanism, asserted via jax.ad_checkpoint.print_saved_residuals).
"""
import contextlib
import io
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, remat


@contextlib.contextmanager
def _mirror(value):
    old = os.environ.get("MXNET_BACKWARD_DO_MIRROR")
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_BACKWARD_DO_MIRROR"]
        else:
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = old


def test_env_parsing():
    for v, expect in [("0", False), ("", False), ("false", False),
                      ("1", True), ("2", True), ("true", True)]:
        with _mirror(v):
            assert remat.mirror_enabled() is expect


def _small_conv_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Dense(4))
    return net


def _gluon_grads(mirror):
    mx.random.seed(7)
    np.random.seed(7)
    net = _small_conv_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    with _mirror(mirror):
        with autograd.record():
            out = net(x)
            loss = (out ** 2).mean()
        loss.backward()
    return [p.grad().asnumpy() for p in net.collect_params().values()
            if p.grad_req != "null"]


def test_gluon_cachedop_grads_match():
    g_off = _gluon_grads("0")
    g_on = _gluon_grads("1")
    assert len(g_off) == len(g_on)
    for a, b in zip(g_off, g_on):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def _module_grads(mirror):
    mx.random.seed(11)
    np.random.seed(11)
    data = mx.sym.Variable("data")
    x = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1))
    x = mx.sym.BatchNorm(x, fix_gamma=False)
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=4)
    sym = mx.sym.SoftmaxOutput(x, name="softmax")
    with _mirror(mirror):
        mod = mx.mod.Module(sym, label_names=("softmax_label",))
        mod.bind(data_shapes=[("data", (2, 3, 8, 8))],
                 label_shapes=[("softmax_label", (2,))])
        mod.init_params(mx.init.Xavier(rnd_type="gaussian"))
        batch = mx.io.DataBatch(
            data=[nd.array(np.random.rand(2, 3, 8, 8).astype("float32"))],
            label=[nd.array(np.array([0.0, 1.0], "float32"))])
        mod.forward(batch, is_train=True)
        mod.backward()
        return [v.asnumpy() for v in mod._exec.grad_dict.values()
                if v is not None]


def test_executor_grads_match():
    g_off = _module_grads("0")
    g_on = _module_grads("1")
    assert len(g_off) == len(g_on) and len(g_on) > 0
    for a, b in zip(g_off, g_on):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_policy_drops_activation_residuals():
    """The memory mechanism itself: under the mirror policy only
    conv/matmul outputs survive as residuals; BN/relu intermediates
    (activation-sized f32[2,8,8,8] here) are rematerialized."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.ad_checkpoint import print_saved_residuals

    def f(p, x):
        for w, g, b in p:
            x = lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            m = x.mean(axis=(0, 2, 3))
            v = ((x - m[None, :, None, None]) ** 2).mean(axis=(0, 2, 3))
            x = (x - m[None, :, None, None]) * \
                (g * lax.rsqrt(v + 1e-5))[None, :, None, None] + \
                b[None, :, None, None]
            x = jnp.maximum(x, 0)
        return (x ** 2).mean()

    p = [(jnp.ones((8, 8, 3, 3)) * 0.01, jnp.ones(8), jnp.zeros(8))
         for _ in range(3)]
    x = jnp.ones((2, 8, 8, 8))

    def n_activation_residuals(fn):
        s = io.StringIO()
        with contextlib.redirect_stdout(s):
            print_saved_residuals(fn, p, x)
        return sum(1 for ln in s.getvalue().splitlines()
                   if "[2,8,8,8]" in ln)

    with _mirror("1"):
        wrapped = remat.maybe_checkpoint(f)
        assert wrapped is not f, "mirror on must wrap"
        plain, mirrored = n_activation_residuals(f), \
            n_activation_residuals(wrapped)
    # plain keeps BN/relu intermediates; mirrored keeps ~one conv output
    # per layer (+ the input)
    assert mirrored < plain, (plain, mirrored)
    assert mirrored <= len(p) + 1, (plain, mirrored)

    with _mirror("0"):
        assert remat.maybe_checkpoint(f) is f, "mirror off must be identity"


# ---------------------------------------------------------------------------
# Conv-tier scoped remat (MXNET_REMAT_POLICY=stage / conv_block):
# blocks declaring a ``_remat_scope`` (the resnet zoo marks stages and
# residual units) are wrapped in jax.checkpoint when traced under a
# CachedOp, keeping only scope-boundary residuals live.  Pinned with an
# exact-arithmetic conv net (integer inputs, 1/4-quantized weights,
# power-of-two pooling windows): recompute reproduces the forward
# exactly, so the remat trajectory must match the no-remat control to
# fp round-off on the single-device AND bucketed-dp paths.
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _policy(value):
    old = os.environ.get("MXNET_REMAT_POLICY")
    os.environ["MXNET_REMAT_POLICY"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_REMAT_POLICY"]
        else:
            os.environ["MXNET_REMAT_POLICY"] = old


def _marked_conv_net(seed=13):
    """Two stages of two conv units, markers at BOTH tiers (the same
    shape the zoo's resnets carry), weights quantized to multiples of
    1/4 so {-1,0,1} inputs keep every intermediate exact in fp32."""
    mx.random.seed(seed)
    np.random.seed(seed)

    def unit(f):
        u = gluon.nn.HybridSequential()
        u.add(gluon.nn.Conv2D(f, 3, padding=1, activation="relu"))
        u._remat_scope = "conv_block"
        return u

    def stage(f):
        s = gluon.nn.HybridSequential()
        s.add(unit(f), unit(f))
        s._remat_scope = "stage"
        return s

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(stage(4), stage(8),
                gluon.nn.GlobalAvgPool2D(),   # 8x8 window: /64, exact
                gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 3, 8, 8), "float32")))  # settle shapes
    for p in net.collect_params().values():
        p.set_data(nd.array(np.round(p.data().asnumpy() * 4.0) / 4.0))
    return net


def _conv_traj(policy, n_dp=1, steps=3, accum=None):
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    with _policy(policy):
        net = _marked_conv_net()
        mesh = make_mesh((n_dp,), ("dp",))
        step = FusedTrainStep(net, gluon.loss.L2Loss(), mesh=mesh,
                              learning_rate=0.25, momentum=0.5,
                              accum_steps=accum)
        rng = np.random.RandomState(2)
        X = nd.array(rng.randint(-1, 2, (8, 3, 8, 8)).astype("float32"))
        y = nd.array(rng.randint(-1, 2, (8, 4)).astype("float32"))
        losses = [float(step(X, y)[0].asnumpy()) for _ in range(steps)]
    params = {k.split("_", 1)[-1]: p.data().asnumpy()
              for k, p in net.collect_params().items()}
    return losses, params


def _assert_traj_equal(a, b):
    (la, pa), (lb, pb) = a, b
    np.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-7)
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_conv_stage_trajectory_matches_none():
    _assert_traj_equal(_conv_traj("none"), _conv_traj("stage"))


def test_conv_block_trajectory_matches_none():
    _assert_traj_equal(_conv_traj("none"), _conv_traj("conv_block"))


def test_conv_stage_trajectory_matches_none_dp2_bucketed():
    """Same identity through the bucketed shard_map exchange."""
    from mxnet_tpu.parallel.mesh import current_device_count

    if current_device_count() < 2:
        pytest.skip("needs 2 devices")
    _assert_traj_equal(_conv_traj("none", n_dp=2),
                       _conv_traj("stage", n_dp=2))


def test_conv_stage_composes_with_grad_accum():
    """Per-stage remat + microbatch accumulation — the ISSUE 17 pair —
    still lands on the no-remat full-batch trajectory."""
    _assert_traj_equal(_conv_traj("none", accum=1),
                       _conv_traj("stage", accum=2))


def test_conv_policies_rematerialize_at_their_tier():
    """The traced step program carries one checkpoint eqn per marked
    block at the SELECTED tier: 2 stages under ``stage``, 4 units under
    ``conv_block`` — and the step's audit metadata declares the policy
    so the analysis auditor can cross-check it offline."""
    from mxnet_tpu import diagnostics as diag
    from mxnet_tpu.analysis import auditor

    for policy, expect in (("stage", 2), ("conv_block", 4)):
        diag.reset_recompile_stats()
        _conv_traj(policy, steps=1)
        fn, specs, meta = diag.recorded_steps()["FusedTrainStep.step"]
        assert meta["remat_policy"] == policy
        _findings, am = auditor.audit_step(
            fn, specs, site="test.remat.%s" % policy,
            remat_policy=policy)
        assert am["n_remat_eqns"] == expect, (policy, am)


def _stage_symbol():
    """Hand-written conv symbol with reference stage naming
    (``stageN_unitM_...``) — the executor's symbol-path segmentation
    keys on these names."""
    d = mx.sym.Variable("data")
    x = mx.sym.Activation(
        mx.sym.Convolution(d, num_filter=4, kernel=(3, 3), pad=(1, 1),
                           name="stem_conv"), act_type="relu")
    for s in (1, 2):
        x = mx.sym.Activation(
            mx.sym.Convolution(x, num_filter=4, kernel=(3, 3),
                               pad=(1, 1),
                               name="stage%d_unit1_conv1" % s),
            act_type="relu", name="stage%d_unit1_relu1" % s)
    fc = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=2,
                               name="head_fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _symbol_fit_params(policy):
    np.random.seed(5)
    mx.random.seed(5)
    X = np.random.rand(32, 3, 8, 8).astype("float32") - 0.5
    y = (np.random.rand(32) > 0.5).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=8,
                           label_name="softmax_label")
    with _policy(policy):
        mod = mx.mod.Module(_stage_symbol(),
                            label_names=("softmax_label",))
        mod.fit(it, num_epoch=3,
                optimizer_params=(("learning_rate", 0.05),))
    params, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in params.items()}


def test_symbol_path_stage_trajectory_matches_none():
    """Module.fit (symbol->apply path) honors MXNET_REMAT_POLICY=stage
    via the executor's stage segmentation: the 3-epoch trained params
    must match the policy=none run bitwise (a remat segment threads its
    exact boundary values — same math, fewer residuals)."""
    p_none = _symbol_fit_params("none")
    p_stage = _symbol_fit_params("stage")
    assert set(p_none) == set(p_stage)
    for k in p_none:
        np.testing.assert_array_equal(p_none[k], p_stage[k], err_msg=k)


def test_symbol_path_stage_rematerializes():
    """The segmentation actually fires: the traced symbol train step
    carries one checkpoint eqn per stage under ``stage`` and zero under
    ``none``."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.analysis.auditor import count_remat_eqns

    def n_eqns(policy):
        with _policy(policy):
            ex = _stage_symbol().simple_bind(
                mx.cpu(), data=(4, 3, 8, 8), softmax_label=(4,))
            step = ex._build_train_step(False)
            args = {k: v._data for k, v in ex.arg_dict.items()}
            aux = {k: v._data for k, v in ex.aux_dict.items()}
            cots = (jnp.ones((4, 2), "float32"),)
            jaxpr = jax.make_jaxpr(
                lambda a, x_, k: step(a, x_, k, cots, 1))(
                    args, aux, jax.random.PRNGKey(0))
        return count_remat_eqns(jaxpr)

    assert n_eqns("none") == 0
    assert n_eqns("stage") == 2


def test_fit_trains_with_mirror_on():
    """End to end: Module.fit converges with the knob on (the knob must
    not break the training loop — reference users flip only the env)."""
    mx.random.seed(3)
    np.random.seed(3)
    n = 64
    X = np.random.rand(n, 1, 8, 8).astype("float32")
    y = (X.mean(axis=(1, 2, 3)) > 0.5).astype("float32")
    X[y > 0.5] += 0.5
    data = mx.sym.Variable("data")
    x = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3), pad=(1, 1))
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=2)
    sym = mx.sym.SoftmaxOutput(x, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    with _mirror("1"):
        mod = mx.mod.Module(sym, label_names=("softmax_label",))
        metric = mx.metric.Accuracy()
        mod.fit(it, num_epoch=6, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.1),),
                eval_metric=metric, initializer=mx.init.Xavier())
    it.reset()
    metric2 = mx.metric.Accuracy()
    score = mod.score(it, metric2)
    acc = dict([score] if isinstance(score, tuple) else score).get(
        "accuracy", metric2.get()[1])
    assert acc > 0.8, acc
