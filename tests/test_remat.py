"""MXNET_BACKWARD_DO_MIRROR — the remat/mirror memory knob.

Reference contract: src/executor/graph_executor.cc:249 (InitFullGraph
mirror augmentation) recomputes activation/BN class nodes in backward to
trade compute for memory; example/image-classification/README.md:370-373
documents the batch-doubling trade.  Here the knob wraps the traced
program in jax.checkpoint with a conv/matmul-saveable policy (remat.py).

Tested: env parsing; gradient equivalence with the knob on vs off on
BOTH the gluon/CachedOp path and the symbolic executor path; and that
the policy genuinely drops activation-sized residuals (the memory
mechanism, asserted via jax.ad_checkpoint.print_saved_residuals).
"""
import contextlib
import io
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, remat


@contextlib.contextmanager
def _mirror(value):
    old = os.environ.get("MXNET_BACKWARD_DO_MIRROR")
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_BACKWARD_DO_MIRROR"]
        else:
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = old


def test_env_parsing():
    for v, expect in [("0", False), ("", False), ("false", False),
                      ("1", True), ("2", True), ("true", True)]:
        with _mirror(v):
            assert remat.mirror_enabled() is expect


def _small_conv_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Dense(4))
    return net


def _gluon_grads(mirror):
    mx.random.seed(7)
    np.random.seed(7)
    net = _small_conv_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    with _mirror(mirror):
        with autograd.record():
            out = net(x)
            loss = (out ** 2).mean()
        loss.backward()
    return [p.grad().asnumpy() for p in net.collect_params().values()
            if p.grad_req != "null"]


def test_gluon_cachedop_grads_match():
    g_off = _gluon_grads("0")
    g_on = _gluon_grads("1")
    assert len(g_off) == len(g_on)
    for a, b in zip(g_off, g_on):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def _module_grads(mirror):
    mx.random.seed(11)
    np.random.seed(11)
    data = mx.sym.Variable("data")
    x = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1))
    x = mx.sym.BatchNorm(x, fix_gamma=False)
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=4)
    sym = mx.sym.SoftmaxOutput(x, name="softmax")
    with _mirror(mirror):
        mod = mx.mod.Module(sym, label_names=("softmax_label",))
        mod.bind(data_shapes=[("data", (2, 3, 8, 8))],
                 label_shapes=[("softmax_label", (2,))])
        mod.init_params(mx.init.Xavier(rnd_type="gaussian"))
        batch = mx.io.DataBatch(
            data=[nd.array(np.random.rand(2, 3, 8, 8).astype("float32"))],
            label=[nd.array(np.array([0.0, 1.0], "float32"))])
        mod.forward(batch, is_train=True)
        mod.backward()
        return [v.asnumpy() for v in mod._exec.grad_dict.values()
                if v is not None]


def test_executor_grads_match():
    g_off = _module_grads("0")
    g_on = _module_grads("1")
    assert len(g_off) == len(g_on) and len(g_on) > 0
    for a, b in zip(g_off, g_on):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_policy_drops_activation_residuals():
    """The memory mechanism itself: under the mirror policy only
    conv/matmul outputs survive as residuals; BN/relu intermediates
    (activation-sized f32[2,8,8,8] here) are rematerialized."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.ad_checkpoint import print_saved_residuals

    def f(p, x):
        for w, g, b in p:
            x = lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            m = x.mean(axis=(0, 2, 3))
            v = ((x - m[None, :, None, None]) ** 2).mean(axis=(0, 2, 3))
            x = (x - m[None, :, None, None]) * \
                (g * lax.rsqrt(v + 1e-5))[None, :, None, None] + \
                b[None, :, None, None]
            x = jnp.maximum(x, 0)
        return (x ** 2).mean()

    p = [(jnp.ones((8, 8, 3, 3)) * 0.01, jnp.ones(8), jnp.zeros(8))
         for _ in range(3)]
    x = jnp.ones((2, 8, 8, 8))

    def n_activation_residuals(fn):
        s = io.StringIO()
        with contextlib.redirect_stdout(s):
            print_saved_residuals(fn, p, x)
        return sum(1 for ln in s.getvalue().splitlines()
                   if "[2,8,8,8]" in ln)

    with _mirror("1"):
        wrapped = remat.maybe_checkpoint(f)
        assert wrapped is not f, "mirror on must wrap"
        plain, mirrored = n_activation_residuals(f), \
            n_activation_residuals(wrapped)
    # plain keeps BN/relu intermediates; mirrored keeps ~one conv output
    # per layer (+ the input)
    assert mirrored < plain, (plain, mirrored)
    assert mirrored <= len(p) + 1, (plain, mirrored)

    with _mirror("0"):
        assert remat.maybe_checkpoint(f) is f, "mirror off must be identity"


def test_fit_trains_with_mirror_on():
    """End to end: Module.fit converges with the knob on (the knob must
    not break the training loop — reference users flip only the env)."""
    mx.random.seed(3)
    np.random.seed(3)
    n = 64
    X = np.random.rand(n, 1, 8, 8).astype("float32")
    y = (X.mean(axis=(1, 2, 3)) > 0.5).astype("float32")
    X[y > 0.5] += 0.5
    data = mx.sym.Variable("data")
    x = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3), pad=(1, 1))
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(mx.sym.Flatten(x), num_hidden=2)
    sym = mx.sym.SoftmaxOutput(x, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    with _mirror("1"):
        mod = mx.mod.Module(sym, label_names=("softmax_label",))
        metric = mx.metric.Accuracy()
        mod.fit(it, num_epoch=6, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.1),),
                eval_metric=metric, initializer=mx.init.Xavier())
    it.reset()
    metric2 = mx.metric.Accuracy()
    score = mod.score(it, metric2)
    acc = dict([score] if isinstance(score, tuple) else score).get(
        "accuracy", metric2.get()[1])
    assert acc > 0.8, acc
