"""RNN stack tests — fused op parity vs torch (the reference's fused RNN is
cuDNN, src/operator/cudnn_rnn-inl.h; torch.nn.LSTM/GRU/RNN share its gate
order and semantics, so CPU torch is the golden model), plus gluon cell/layer
behavior mirroring tests/python/unittest/test_gluon_rnn.py."""
import numpy as np
import pytest
import torch

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import rnn
from mxnet_tpu.ops.rnn import rnn_param_size


def _flat_from_torch(tm, num_layers, bidir):
    nd_ = 2 if bidir else 1
    ws, bs = [], []
    for l in range(num_layers):
        for d in range(nd_):
            sfx = "_l%d%s" % (l, "_reverse" if d else "")
            ws += [getattr(tm, "weight_ih" + sfx).detach().numpy().ravel(),
                   getattr(tm, "weight_hh" + sfx).detach().numpy().ravel()]
    for l in range(num_layers):
        for d in range(nd_):
            sfx = "_l%d%s" % (l, "_reverse" if d else "")
            bs += [getattr(tm, "bias_ih" + sfx).detach().numpy().ravel(),
                   getattr(tm, "bias_hh" + sfx).detach().numpy().ravel()]
    return np.concatenate(ws + bs)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh", "rnn_relu"])
@pytest.mark.parametrize("layers,bidir", [(1, False), (2, True)])
def test_fused_rnn_vs_torch(mode, layers, bidir):
    T, N, I, H = 5, 3, 4, 6
    torch.manual_seed(0)
    if mode == "lstm":
        tm = torch.nn.LSTM(I, H, layers, bidirectional=bidir)
    elif mode == "gru":
        tm = torch.nn.GRU(I, H, layers, bidirectional=bidir)
    else:
        tm = torch.nn.RNN(I, H, layers, bidirectional=bidir,
                          nonlinearity=mode[4:])
    x = torch.randn(T, N, I)
    ndir = 2 if bidir else 1
    h0 = torch.randn(layers * ndir, N, H)
    if mode == "lstm":
        c0 = torch.randn(layers * ndir, N, H)
        out_t, (h_t, c_t) = tm(x, (h0, c0))
    else:
        out_t, h_t = tm(x, h0)

    flat = _flat_from_torch(tm, layers, bidir)
    assert flat.size == rnn_param_size(layers, I, H, bidir, mode)
    args = [nd.array(x.numpy()), nd.array(flat), nd.array(h0.numpy())]
    if mode == "lstm":
        args.append(nd.array(c0.numpy()))
    out = nd.RNN(*args, state_size=H, num_layers=layers, bidirectional=bidir,
                 mode=mode, state_outputs=True, _training=False)
    np.testing.assert_allclose(out[0].asnumpy(), out_t.detach().numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(out[1].asnumpy(), h_t.detach().numpy(),
                               atol=1e-5)
    if mode == "lstm":
        np.testing.assert_allclose(out[2].asnumpy(), c_t.detach().numpy(),
                                   atol=1e-5)


def test_fused_rnn_grad():
    """Backward through the fused op produces finite, nonzero grads."""
    T, N, I, H = 4, 2, 3, 5
    x = nd.random.uniform(shape=(T, N, I))
    flat = nd.random.uniform(shape=(rnn_param_size(1, I, H, False, "lstm"),))
    h0 = nd.zeros((1, N, H))
    c0 = nd.zeros((1, N, H))
    for a in (x, flat):
        a.attach_grad()
    with autograd.record():
        out = nd.RNN(x, flat, h0, c0, state_size=H, num_layers=1,
                     mode="lstm", state_outputs=False)
        loss = (out * out).sum()
    loss.backward()
    assert np.isfinite(flat.grad.asnumpy()).all()
    assert np.abs(x.grad.asnumpy()).sum() > 0


@pytest.mark.parametrize("cell_cls,n_states",
                         [(rnn.RNNCell, 1), (rnn.LSTMCell, 2),
                          (rnn.GRUCell, 1)])
def test_cell_unroll_shapes(cell_cls, n_states):
    cell = cell_cls(100, prefix="rnn_", input_size=50)
    cell.initialize()
    inputs = [nd.ones((10, 50)) for _ in range(3)]
    outputs, states = cell.unroll(3, inputs)
    assert len(outputs) == 3
    assert len(states) == n_states
    for o in outputs:
        assert o.shape == (10, 100)


def test_cell_matches_fused_layer():
    """Cell stepping == fused scan layer when sharing parameters."""
    T, N, I, H = 4, 2, 3, 5
    layer = rnn.LSTM(H, input_size=I)
    layer.initialize()
    x = nd.random.uniform(shape=(T, N, I))
    out = layer(x)

    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, x, layout="TNC")
    stacked = nd.stack(*outs, axis=0)
    np.testing.assert_allclose(out.asnumpy(), stacked.asnumpy(), atol=1e-5)


def test_unroll_valid_length():
    """Masked outputs + final state taken at each sample's last valid step."""
    T, N, I, H = 5, 3, 4, 6
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    x = nd.random.uniform(shape=(N, T, I))
    vl = nd.array([2, 5, 3])
    outputs, states = cell.unroll(T, x, layout="NTC", merge_outputs=True,
                                  valid_length=vl)
    out_np = nd.stack(*[outputs[t] for t in range(T)], axis=0).asnumpy() \
        if isinstance(outputs, list) else outputs.asnumpy()
    # outputs past valid_length are zeroed (axis order TNC after stack)
    assert np.abs(out_np[3:, 0]).sum() == 0
    assert np.abs(out_np[:2, 0]).sum() > 0
    # state equals the unmasked run truncated at valid_length
    outs2, states2 = cell.unroll(2, x[:, :2, :], layout="NTC")
    np.testing.assert_allclose(states[0].asnumpy()[0],
                               states2[0].asnumpy()[0], atol=1e-5)
    np.testing.assert_allclose(states[1].asnumpy()[0],
                               states2[1].asnumpy()[0], atol=1e-5)


def test_bidirectional_valid_length():
    """Reverse direction must not consume padding before real tokens."""
    T, N, I, H = 4, 2, 3, 5
    cell = rnn.BidirectionalCell(rnn.LSTMCell(H, input_size=I),
                                 rnn.LSTMCell(H, input_size=I))
    cell.initialize()
    x = nd.random.uniform(shape=(N, T, I))
    vl = nd.array([2, 4])
    outputs, _ = cell.unroll(T, x, layout="NTC", valid_length=vl)
    # sample 0 truncated run (length 2) must match the padded run's first 2
    l_cell, r_cell = cell._children.values()
    short = rnn.BidirectionalCell(l_cell, r_cell)
    outs_short, _ = short.unroll(2, x[0:1, :2, :], layout="NTC")
    np.testing.assert_allclose(outputs[0].asnumpy()[0],
                               outs_short[0].asnumpy()[0], atol=1e-5)
    np.testing.assert_allclose(outputs[1].asnumpy()[0],
                               outs_short[1].asnumpy()[0], atol=1e-5)


def test_sequential_and_modifier_cells():
    net = rnn.SequentialRNNCell()
    net.add(rnn.LSTMCell(8, input_size=4))
    net.add(rnn.ResidualCell(rnn.GRUCell(8, input_size=8)))
    net.add(rnn.DropoutCell(0.5))
    net.initialize()
    outputs, states = net.unroll(3, [nd.ones((2, 4))] * 3)
    assert outputs[-1].shape == (2, 8)
    assert len(states) == 3  # lstm 2 + gru 1


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(rnn.LSTMCell(5, input_size=3),
                                 rnn.LSTMCell(5, input_size=3))
    cell.initialize()
    outputs, states = cell.unroll(4, nd.ones((2, 4, 3)), layout="NTC")
    assert len(outputs) == 4
    assert outputs[0].shape == (2, 10)
    assert len(states) == 4


@pytest.mark.parametrize("layer_cls,mode",
                         [(rnn.LSTM, "lstm"), (rnn.GRU, "gru"),
                          (rnn.RNN, "rnn")])
def test_layer_forward_backward(layer_cls, mode):
    layer = layer_cls(7, num_layers=2, bidirectional=True, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(2, 5, 3))
    x.attach_grad()
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 5, 14)
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_layer_states_roundtrip():
    layer = rnn.LSTM(6, num_layers=1)
    layer.initialize()
    x = nd.random.uniform(shape=(3, 2, 4))
    states = layer.begin_state(batch_size=2)
    out, new_states = layer(x, states)
    assert out.shape == (3, 2, 6)
    assert new_states[0].shape == (1, 2, 6)
    assert new_states[1].shape == (1, 2, 6)
    # stepping with returned states keeps shapes stable
    out2, _ = layer(x, new_states)
    assert out2.shape == (3, 2, 6)


def test_layer_save_load_roundtrip(tmp_path):
    layer = rnn.GRU(5, num_layers=2, input_size=3)
    layer.initialize()
    x = nd.random.uniform(shape=(4, 2, 3))
    ref = layer(x).asnumpy()
    path = str(tmp_path / "gru.params")
    layer.save_parameters(path)
    layer2 = rnn.GRU(5, num_layers=2, input_size=3)
    layer2.load_parameters(path)
    np.testing.assert_allclose(layer2(x).asnumpy(), ref, atol=1e-6)
