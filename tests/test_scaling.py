"""Scaling-efficiency harness (north-star metric #2, BASELINE.md).

Reference bar: resnet-152 dist_device_sync reaches 90.1% scaling
efficiency at 256 GPUs (example/image-classification/README.md:309-319).
Real multi-chip is unreachable here; these tests pin the proxies:
HLO collective accounting, cross-device-count numeric consistency, and
the ring-allreduce projection model.
"""
import numpy as np
import pytest

from mxnet_tpu.parallel import scaling


def test_collective_stats_parses_hlo_forms():
    hlo = """
  %ar = f32[1000]{0} all-reduce(f32[1000]{0} %p0), replica_groups={}
  %t = (f32[64,3,7,7]{3,2,1,0}, f32[64]{0}) all-reduce(%a, %b), to_apply=%add
  %ag-start = f32[8,128]{1,0} all-gather-start(f32[1,128]{1,0} %x), dimensions={0}
  %ag-done = f32[8,128]{1,0} all-gather-done(%ag-start)
  %unrelated = f32[4]{0} add(f32[4]{0} %u, f32[4]{0} %v)
"""
    out = scaling.collective_stats(hlo)
    assert out["all-reduce"]["count"] == 2
    assert out["all-reduce"]["bytes"] == 4 * (1000 + 64 * 3 * 7 * 7 + 64)
    assert out["all-gather"]["count"] == 1  # -start counted, -done not
    assert out["all-gather"]["bytes"] == 4 * 8 * 128
    assert "add" not in out


def test_projection_model_shape():
    proj = scaling.project_efficiency(
        grad_bytes=102_000_000, step_time_s=0.0138)
    eff = proj["projected_efficiency"]
    assert set(eff) == {"8", "16", "32", "64", "128", "256"}
    # efficiency decreases with chip count, stays in (0, 1]
    vals = [eff[k] for k in ("8", "16", "32", "64", "128", "256")]
    assert all(0 < v <= 1 for v in vals)
    assert vals == sorted(vals, reverse=True)
    assert proj["reference_resnet152_256gpu"] == 0.901


@pytest.mark.slow
def test_sweep_consistency_and_collectives():
    out = scaling.sweep(device_counts=(1, 2, 4), steps=3, batch=8)
    rows = {r["n"]: r for r in out["sweep"] if "losses" in r}
    assert set(rows) == {1, 2, 4}, out
    for n in (2, 4):
        assert rows[n]["numerically_consistent"], rows[n]
        ar = rows[n]["collectives"]["all-reduce"]
        # the gradient exchange must be real: >= resnet18's ~44 MB of
        # parameters go over the wire every step
        assert ar["bytes"] > 40e6, ar


@pytest.mark.slow
def test_control_sweep_fp64_and_lr0():
    """VERDICT r3 item 6: the drift-is-chaos claim made falsifiable.
    fp64 multi-step trajectories must agree across n to 1e-9 (a real
    sharding bug would not shrink with precision); lr=0 trajectories
    must be flat and equal at first-step tolerance."""
    out = scaling.control_sweep(device_counts=(1, 2), steps=3, batch=8)
    for name in ("fp64", "lr0"):
        blk = out[name]
        assert blk["all_consistent"], blk
        rows = [r for r in blk["sweep"] if r.get("n") == 2]
        assert rows and rows[0]["multi_step_consistent"], blk
    # fp64's drift must be orders below fp32's first-step tolerance
    fp64_row = [r for r in out["fp64"]["sweep"] if r.get("n") == 2][0]
    assert fp64_row["multi_step_rel_drift"] < 1e-9


@pytest.mark.slow
def test_mp_placement_sweep_matches():
    """The ctx_group model-parallel LSTM (reference lstm.py) trained on
    1 vs 2 device groups: placement must not change the training math
    beyond per-program fp reorder noise."""
    out = scaling.mp_placement_sweep()
    assert out["trajectories_match"], out
    assert out["max_rel_diff"] < 1e-3
    assert len(out["ngpu1"]["train_nll"]) >= 2
