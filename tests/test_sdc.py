"""Silent-data-corruption defense (ISSUE 15): cross-rank fingerprint
voting, supervisor quarantine, and the offline replay audit.

Unit level: fingerprint/vote semantics ride the tier-1 CLI self-test;
here the python-level surfaces — the in-graph detector on a CPU dp
mesh (a per-device flipped bit is named by device index), the exit-87
contract under supervision, the conv-path divergence-guard wiring, and
the replay audit catching a poisoned-but-sha256-verified checkpoint
chain.  E2e: a supervised 2-worker dist_sync fleet whose rank 1
suffers a chaos ``bitflip_param`` is named by the vote (rank + step +
bucket in the flight dump's ``sdc`` event), exits 87, is QUARANTINED
(no rejoin), and the fleet reshapes 2→1 and resumes from the newest
verified checkpoint with final params matching the uninterrupted
control at the PR-8 tolerance — zero operator action."""
import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos as chaos_mod
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu import diagnostics as diag
from mxnet_tpu import sdc
from mxnet_tpu.elastic import FleetSupervisor

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import launch  # noqa: E402  (tools/launch.py)

_ELASTIC_WORKER = os.path.join(os.path.dirname(__file__),
                               "elastic_worker.py")


def _child_env(extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_SDC_CHECK_EVERY_N", None)
    env.update(extra or {})
    return env


# ---------------------------------------------------------------------
# tier-1 CLI: the no-jax detector units
# ---------------------------------------------------------------------
def test_sdc_self_test_cli():
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.sdc", "--self-test"],
        capture_output=True, text=True, env=_child_env(), cwd=ROOT,
        timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["self_test_ok"], out


# ---------------------------------------------------------------------
# unit: fingerprints + vote (the python surfaces the CLI rides)
# ---------------------------------------------------------------------
def test_fingerprint_bitflip_and_vote():
    a = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    fp0 = sdc.fingerprint_np(a)
    b = chaos_mod.flip_bit_np(a.copy(), 77).reshape(a.shape)
    assert sdc.fingerprint_np(b) != fp0
    # W=3 names the minority and its bucket; W=2 needs the reference
    good, bad = [fp0, 7], [sdc.fingerprint_np(b), 7]
    v = sdc.vote({0: good, 1: good, 2: bad})
    assert v["conclusive"] and v["minority"] == [2]
    assert v["mismatched_buckets"][2]["buckets"] == [0]
    v2 = sdc.vote({0: good, 1: bad})
    assert not v2["conclusive"]
    v3 = sdc.vote({0: good, 1: bad}, reference=good)
    assert v3["conclusive"] and v3["minority"] == [1]


def test_guard_trip_exits_87_under_supervisor():
    code = (
        "import os\n"
        "os.environ['MXNET_ELASTIC_SUPERVISED'] = '1'\n"
        "from mxnet_tpu import sdc\n"
        "g = sdc.SDCGuard(every_n=1)\n"
        "g.apply({0: [1, 2], 1: [1, 9]}, step=4, my_rank=1,\n"
        "        reference_fn=lambda: [1, 2])\n"
        "raise SystemExit('unreachable: apply must os._exit(87)')\n")
    res = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True,
                         env=_child_env(), timeout=300)
    assert res.returncode == sdc.EXIT_SDC, \
        (res.returncode, res.stdout, res.stderr)


# ---------------------------------------------------------------------
# the in-graph detector: a per-device flipped bit on a CPU dp mesh is
# caught by the gathered fingerprint rows and NAMED by device index
# ---------------------------------------------------------------------
def _corrupt_one_device(mesh, arr, device_index, bit):
    """A 'replicated' (P()) array whose ``device_index`` replica holds
    a flipped bit — exactly what a corrupt chip's HBM would hold."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    host = np.asarray(arr)
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        h = host if i != device_index else \
            chaos_mod.flip_bit_np(host.copy(), bit).reshape(host.shape)
        bufs.append(jax.device_put(h, d))
    return jax.make_array_from_single_device_arrays(
        host.shape, NamedSharding(mesh, P()), bufs)


def test_transformer_mesh_detector_names_device(monkeypatch):
    import jax

    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.transformer import (LMTokenIter, TransformerConfig,
                                       TransformerTrainStep)

    monkeypatch.setenv("MXNET_SDC_CHECK_EVERY_N", "1")
    monkeypatch.delenv("MXNET_ELASTIC_SUPERVISED", raising=False)
    mesh = make_mesh((3,), ("dp",), jax.devices()[:3])
    cfg = TransformerConfig(vocab_size=64, n_layers=1, d_model=16,
                            n_heads=2, d_ff=32)
    s = TransformerTrainStep(cfg, mesh=mesh, seed=0)
    it = LMTokenIter(batch_size=6, seq_len=8, vocab_size=64,
                     num_sequences=24)
    b = it.next()
    s.step(b.data[0], b.label[0])
    rows = np.asarray(s.sdc_rows(s._sdc_ctr))
    assert rows.shape[0] == 3 and rows.any()
    assert np.array_equal(rows[0], rows[1]) \
        and np.array_equal(rows[0], rows[2])
    guard = sdc.SDCGuard(every_n=1)
    assert guard.check_rows(rows, step=1)["ok"]

    # flip one bit on device 2's replica only: the next step's rows
    # disagree and the W=3 vote names device 2 (and its bucket)
    name = sorted(s._params)[0]
    s._params[name] = _corrupt_one_device(mesh, s._params[name], 2, 12)
    s.step(b.data[0], b.label[0])
    rows = np.asarray(s.sdc_rows(s._sdc_ctr))
    assert not np.array_equal(rows[0], rows[2])
    with pytest.raises(sdc.SDCError) as ei:
        guard.check_rows(rows, step=2)
    assert "(2) at step 2" in str(ei.value)  # device 2 named
    assert "bucket(s) [0]" in str(ei.value)
    # the flight-recorder 'sdc' event carries (rank, step, bucket,
    # expected-vs-got) — the post-mortem evidence the dump persists
    _hdr, entries = diag.recorder.snapshot()
    ev = [e for e in entries if e["op"] == "sdc"]
    assert ev, "no sdc flight event recorded"
    args = ev[-1]["args"]
    assert args["step"] == 2 and args["minority_rank"] == 2
    assert args["buckets"] and args["detail"]


def test_fused_step_sdc_rows(monkeypatch):
    import jax

    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("MXNET_SDC_CHECK_EVERY_N", "2")
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    fts = FusedTrainStep(net, SoftmaxCrossEntropyLoss(), mesh=mesh)
    X = mx.nd.array(np.random.RandomState(0).randn(8, 6)
                    .astype("float32"))
    y = mx.nd.array((np.arange(8) % 4).astype("float32"))
    for _ in range(4):
        fts(X, y)
    assert fts.bucketed and fts._sdc
    rows = np.asarray(fts._last_sdc_rows)
    assert rows.shape[0] == 2 and rows.any()
    assert np.array_equal(rows[0], rows[1])
    # cadence: step 3 (odd) computes zeros under the cond — the
    # param-bytes pass is only paid every MXNET_SDC_CHECK_EVERY_N
    fts(X, y)
    assert not np.asarray(fts._last_sdc_rows).any()


def test_sdc_off_by_default_unchanged_step(monkeypatch):
    """MXNET_SDC_CHECK_EVERY_N unset: the step builds without the
    fingerprint output — the off path is the exact pre-SDC graph."""
    import jax

    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    monkeypatch.delenv("MXNET_SDC_CHECK_EVERY_N", raising=False)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    fts = FusedTrainStep(net, SoftmaxCrossEntropyLoss(), mesh=mesh)
    X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                    .astype("float32"))
    y = mx.nd.array((np.arange(8) % 4).astype("float32"))
    loss, logits = fts(X, y)
    assert not fts._sdc and fts._last_sdc_rows is None
    assert np.isfinite(float(loss.asnumpy().mean()))


# ---------------------------------------------------------------------
# satellite: the conv-path divergence guard (transformer parity)
# ---------------------------------------------------------------------
def _tiny_module():
    from mxnet_tpu import sym

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=8)
    net = sym.SoftmaxOutput(data=net, name="softmax")
    return mx.mod.Module(symbol=net, context=mx.cpu())


def test_divergence_guard_wired_into_module_fit(monkeypatch):
    monkeypatch.setenv("MXNET_DIVERGENCE_WINDOW", "2")
    monkeypatch.delenv("MXNET_ELASTIC_SUPERVISED", raising=False)
    steps = []

    def fake_check(self, loss, step=None):
        steps.append(step)
        return step == 3

    monkeypatch.setattr(diag.DivergenceGuard, "check", fake_check)
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y = (np.arange(16) % 4).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    mod = _tiny_module()
    with pytest.raises(diag.DivergenceError):
        mod.fit(it, num_epoch=2, optimizer="sgd", kvstore="local",
                eval_metric="ce")
    assert steps == [1, 2, 3]


def test_divergence_guard_sees_per_step_loss_not_running_mean(
        monkeypatch):
    """The conv-path guard recovers the PER-STEP loss from the
    metric's (sum, count) deltas: a 7x spike on batch 20 of an epoch
    trips, where the epoch-running mean (~(19·2+14)/20 ≈ 2.5, under
    the 3x-median threshold) would have diluted it into invisibility."""
    monkeypatch.setenv("MXNET_DIVERGENCE_WINDOW", "4")
    monkeypatch.setenv("MXNET_DIVERGENCE_FACTOR", "3.0")
    monkeypatch.delenv("MXNET_ELASTIC_SUPERVISED", raising=False)
    seen = []
    orig = diag.DivergenceGuard.check

    def spy(self, loss, step=None):
        seen.append((step, float(loss)))
        return orig(self, loss, step=step)

    monkeypatch.setattr(diag.DivergenceGuard, "check", spy)
    rng = np.random.RandomState(0)
    x = rng.randn(80, 4).astype(np.float32) * 0.01
    y = np.zeros(80, dtype=np.float32)
    x[76:] = np.abs(x[76:]) * 1e7  # batch 20 is garbage
    y[76:] = 3
    it = mx.io.NDArrayIter(x, y, batch_size=4, shuffle=False)
    mod = _tiny_module()
    with pytest.raises(diag.DivergenceError):
        mod.fit(it, num_epoch=1, optimizer="sgd", kvstore="local",
                eval_metric="ce")
    step, spike = seen[-1]
    assert step == 20 and spike > 7.0, seen[-3:]
    # the 19 clean steps fed ~flat per-batch values, not a drifting
    # cumulative mean polluted by the spike
    prior = [v for _s, v in seen[:-1]]
    assert max(prior) < 2.5, prior


def test_loss_signal_picks_loss_like_metric():
    assert diag.loss_signal([("accuracy", 0.9),
                             ("cross-entropy", 1.7)]) == 1.7
    assert diag.loss_signal([("accuracy", 0.9)]) is None
    # a non-finite metric is garbage whatever its name
    assert diag.loss_signal([("accuracy", float("nan"))]) != \
        diag.loss_signal([("accuracy", 0.9)])


def test_bitflip_grad_injected_in_module_fit(monkeypatch):
    """bitflip_grad fires in the mid-step window and training carries
    on — the uniform-corruption case only the replay audit can catch
    (there is no cross-rank disagreement to vote on)."""
    monkeypatch.setenv("MXNET_CHAOS", "bitflip_grad:rank=0,step=2")
    chaos_mod.reset()
    try:
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        y = (np.arange(16) % 4).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=4)
        mod = _tiny_module()
        mod.fit(it, num_epoch=1, optimizer="sgd", kvstore="local")
        assert chaos_mod.injected_total("bitflip_grad") == 1
    finally:
        monkeypatch.delenv("MXNET_CHAOS")
        chaos_mod.reset()


# ---------------------------------------------------------------------
# replay audit: the offline corruption bisector
# ---------------------------------------------------------------------
def test_replay_audit_clean_and_poisoned(tmp_path, monkeypatch):
    from mxnet_tpu.transformer import (LMTokenIter, TransformerConfig,
                                       TransformerTrainStep)

    cfg = TransformerConfig(vocab_size=64, n_layers=1, d_model=16,
                            n_heads=2, d_ff=32)

    def run(d, chaos=None):
        if chaos:
            monkeypatch.setenv("MXNET_CHAOS", chaos)
        else:
            monkeypatch.delenv("MXNET_CHAOS", raising=False)
        chaos_mod.reset()
        try:
            s = TransformerTrainStep(cfg, seed=0)
            it = LMTokenIter(batch_size=4, seq_len=8, vocab_size=64,
                             num_sequences=16)
            s.fit(it, 6, checkpoint_every_n=2, checkpoint_dir=str(d))
        finally:
            monkeypatch.delenv("MXNET_CHAOS", raising=False)
            chaos_mod.reset()

    # clean run: every interval reproduces its successor bitwise
    clean = tmp_path / "clean"
    run(clean)
    rep = sdc.replay_audit(str(clean), step=2)
    assert rep["match"] and rep["steps_replayed"] == 2, rep
    # the next MANIFEST carries the per-param fingerprints the audit
    # compares against (shard-independent comparison target)
    assert rep["manifest_fps"] == {"present": True, "match": True,
                                   "mismatched_keys": []}, rep
    man = ckpt.read_manifest(str(clean), 4)
    assert man["shards"]["0"]["param_fps"], man
    assert sdc.replay_bisect(str(clean))["ok"]

    # poisoned run: a W=1 bitflip at step 3 that the VOTE cannot see
    # and sha256 verifies (the bytes on disk ARE the bytes written) —
    # the replay audit bisects the corruption to the (2, 4) interval
    bad = tmp_path / "bad"
    run(bad, chaos="bitflip_param:rank=0,step=3")
    assert ckpt.verify_dir(str(bad))["ok"], \
        "sha256 must PASS — the corruption is pre-write"
    rep = sdc.replay_bisect(str(bad))
    assert not rep["ok"] and rep["first_corrupt_interval"] == (2, 4), rep

    # the CLI exits 3 on the mismatch, 0 on the clean chain
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.sdc", "--replay", str(bad),
         "--json"],
        capture_output=True, text=True, env=_child_env(), cwd=ROOT,
        timeout=600)
    assert res.returncode == 3, (res.returncode, res.stdout,
                                 res.stderr)
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["first_corrupt_interval"] == [2, 4], out


# ---------------------------------------------------------------------
# e2e acceptance: supervised 2-worker fleet + bitflip on rank 1 →
# the vote names rank 1 (flight 'sdc' event with step + bucket), rank
# exits 87, the supervisor QUARANTINES the slot (rejoin marker
# ignored), reshapes 2→1, resumes from the newest verified checkpoint,
# and the final params match the uninterrupted control — zero operator
# action; --health renders the quarantine in the restart timeline
# ---------------------------------------------------------------------
def test_sdc_quarantine_reshape_resume_e2e(tmp_path, monkeypatch):
    # control: uninterrupted 2-worker cluster (same worker script)
    ctrl_prefix = str(tmp_path / "control")
    codes = launch.launch_local(
        2, 1, [sys.executable, _ELASTIC_WORKER, ctrl_prefix],
        env=_child_env({
            "MXNET_CKPT_DIR": str(tmp_path / "ck_ctrl"),
            "MXNET_CKPT_ASYNC": "0",
            "MXNET_DUMP_DIR": str(tmp_path / "dumps_ctrl"),
        }))
    assert codes == [0, 0], codes
    control = np.load(ctrl_prefix + "_rank0.npz")

    ck = str(tmp_path / "ck")
    state_dir = str(tmp_path / "sup")
    dumps = str(tmp_path / "dumps")
    monkeypatch.setenv("MXNET_CHAOS", "bitflip_param:rank=1,step=3")
    chaos_mod.reset()
    out_prefix = str(tmp_path / "sup_out")
    sup = FleetSupervisor(
        [sys.executable, _ELASTIC_WORKER, out_prefix, "0.2"],
        num_workers=2, num_servers=1, mode="ps", state_dir=state_dir,
        ckpt_dir=ck, max_restarts=3, backoff_s=0.05, rejoin_s=1.0,
        jitter=False, monitor_interval_s=0.05, drain_s=20.0,
        env=_child_env({
            "MXNET_CKPT_ASYNC": "0",
            "MXNET_SDC_CHECK_EVERY_N": "1",
            "MXNET_PS_HEARTBEAT_INTERVAL": "0.2",
            "MXNET_KVSTORE_SYNC_TIMEOUT": "8",
            "MXNET_FLIGHT_RECORDER_DUMP": "1",
            "MXNET_DUMP_DIR": dumps,
        }))
    try:
        rc = sup.run()
    finally:
        monkeypatch.delenv("MXNET_CHAOS")
        chaos_mod.reset()
    assert rc == 0, sup.events

    # the detector fired: the corrupt worker exited 87 and its SLOT
    # was quarantined (the kvstore registration race decides which
    # spawn slot carries kv rank 1, so the slot index is whichever
    # machine the corrupt rank ran on), and gen 1 launched at W'=1
    # resuming a verified step
    sdc_exits = [e for e in sup.events if e["kind"] == "worker_exit"
                 and e["exit_code"] == sdc.EXIT_SDC]
    assert len(sdc_exits) == 1, sup.events
    bad_slot = sdc_exits[0]["slot"]
    assert any(e["kind"] == "fleet_down" and e["reason"] == "sdc"
               for e in sup.events), sup.events
    assert any(e["kind"] == "slot_quarantined"
               and e["slot"] == bad_slot
               for e in sup.events), sup.events
    assert not any(e["kind"] == "slots_rejoined"
                   for e in sup.events), sup.events
    launches = [e for e in sup.events if e["kind"] == "launch"]
    assert [e["world_size"] for e in launches] == [2, 1], launches
    assert launches[1]["resume_step"] >= 2, launches
    assert sup.slots.quarantined() == [bad_slot]

    # the corrupt rank's flight dump carries the 'sdc' event naming
    # (rank, step, bucket, expected-vs-got)
    dump_path = os.path.join(dumps, "gen0",
                             "flightrecorder_rank1.json")
    assert os.path.exists(dump_path), os.listdir(
        os.path.join(dumps, "gen0"))
    with open(dump_path) as f:
        payload = json.load(f)
    assert payload["header"]["reason"] == "sdc", payload["header"]
    ev = [e for e in payload["entries"] if e["op"] == "sdc"]
    assert ev, "no sdc event in the flight dump"
    args = ev[-1]["args"]
    assert args["minority_rank"] == 1 and args["self_rank"] == 1
    assert args["step"] == 3, args
    assert args["buckets"], args
    assert args["detail"], args

    # zero operator action, same final params as the control (the
    # global batch sequence replays exactly at W'=1 — the PR-8
    # elastic tolerance; the flipped bit never reached rank 0 or a
    # checkpoint shard)
    resumed = np.load(out_prefix + "_rank0.npz")
    assert sorted(control.files) == sorted(resumed.files)
    for k in control.files:
        np.testing.assert_allclose(
            resumed[k], control[k], rtol=2e-6, atol=1e-7,
            err_msg="post-quarantine elastic resume diverged on %s" % k)

    # --health over both generations + the journal: the restart
    # timeline names the quarantine; the recovered fleet exits 0
    dump_files = sorted(glob.glob(os.path.join(
        dumps, "gen*", "flightrecorder_rank*.json")))
    assert dump_files
    tool = os.path.join(ROOT, "tools", "merge_traces.py")
    res = subprocess.run(
        [sys.executable, tool, "--health",
         os.path.join(state_dir, "supervisor_events.json")]
        + dump_files,
        capture_output=True, text=True, timeout=300)
    assert "RESTART TIMELINE: 2 generation(s)" in res.stdout, res.stdout
    assert "slot %d QUARANTINED (sdc)" % bad_slot in res.stdout, \
        res.stdout
    assert "gen 1: W=1, resumed from step" in res.stdout, res.stdout
    assert res.returncode == 0, (res.returncode, res.stdout)
