"""Serving-tier tests: the batching model server's robustness layer.

The training side proved it survives preemption and desync (PR 7);
these tests prove the INFERENCE side degrades correctly when overload
and partial failure are the steady state: bounded queues shed excess
load with accounting, deadlines expire work before dispatch instead of
batching it, the circuit breaker fast-fails a broken model, drain
completes every admitted request, and the chaos-injected overload e2e
holds admitted p99 under the deadline while 2x-capacity traffic is
shed."""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import chaos
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu import diagnostics as diag
from mxnet_tpu import serving

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SERVE_WORKER = os.path.join(os.path.dirname(__file__),
                             "serve_worker.py")


def _child_env(extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": ROOT + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_SERVE_QUEUE_MAX", None)
    env.update(extra or {})
    return env


# ---------------------------------------------------------------------
# CLI self-test (the satellite: tier-1 covers queue admission, deadline
# expiry, breaker trip/reset, drain ordering)
# ---------------------------------------------------------------------
def test_serving_self_test():
    res = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving", "--self-test"],
        capture_output=True, text=True, env=_child_env(), cwd=ROOT,
        timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    payload = json.loads(res.stdout.splitlines()[-1])
    assert payload["self_test_ok"], payload


# ---------------------------------------------------------------------
# runtime: buckets, AOT compile, padding, checkpoint loading
# ---------------------------------------------------------------------
def test_plan_batch_buckets():
    assert serving.plan_batch_buckets(32) == (1, 2, 4, 8, 16, 32)
    assert serving.plan_batch_buckets(6) == (1, 2, 4, 6)
    assert serving.plan_batch_buckets(1) == (1,)
    # explicit ladders are deduped/sorted and always include the cap
    assert serving.plan_batch_buckets(16, [4, 8, 4]) == (4, 8, 16)


def test_runtime_padding_matches_unpadded():
    rt = serving.demo_runtime(max_batch=8)
    rt.compile(warmup=True)
    assert rt.compiled
    x = np.random.RandomState(0).randn(3, 16).astype("float32")
    cls3, logits3 = rt.execute(x)
    assert cls3.shape == (3,) and logits3.shape == (3, 4)
    cls1, logits1 = rt.execute(x[:1])
    assert int(cls1[0]) == int(cls3[0])
    np.testing.assert_allclose(np.float64(logits1[0]),
                               np.float64(logits3[0]), rtol=1e-6)


def test_runtime_bf16_compute_dtype():
    rt = serving.demo_runtime(max_batch=2)
    # params were cast once at load
    assert str(rt._params["w1"].dtype) == "bfloat16"
    rt32 = serving.demo_runtime(max_batch=2, compute_dtype=None)
    assert str(rt32._params["w1"].dtype) == "float32"


def test_runtime_from_checkpoint(tmp_path):
    d = str(tmp_path / "ckpt")
    params = {"w1": np.random.RandomState(1).randn(16, 32)
              .astype("float32"),
              "b1": np.zeros(32, dtype="float32"),
              "w2": np.random.RandomState(2).randn(32, 4)
              .astype("float32"),
              "b2": np.zeros(4, dtype="float32")}
    ckpt.save_checkpoint(d, 7, params=params)

    def apply_fn(p, aux, x):
        import jax.numpy as jnp

        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    rt = serving.ModelRuntime.from_checkpoint(
        "ck", d, apply_fn, sample_shape=(16,), max_batch=4)
    rt.compile(warmup=True)
    out = rt.execute(np.ones((2, 16), dtype="float32"))
    assert out.shape == (2, 4)
    assert "step7" in rt.source or "step 7" in rt.source or \
        "00000007" in rt.source or "7" in rt.source


def test_runtime_from_checkpoint_names_missing_ranks(tmp_path):
    """Server startup must explain WHY a model won't load: the exact
    ranks whose shards are missing (the checkpoint satellite)."""
    d = str(tmp_path / "ckpt2")
    ckpt.CheckpointManager(d, rank=0, num_ranks=2).save(
        5, params={"w": np.ones(3, dtype="float32")}, blocking=True)
    with pytest.raises(FileNotFoundError) as ei:
        serving.ModelRuntime.from_checkpoint(
            "ck", d, lambda p, a, x: x, sample_shape=(3,),
            num_ranks=2, rank=1)
    msg = str(ei.value)
    assert "rank(s) [1]" in msg and "of 2" in msg, msg


# ---------------------------------------------------------------------
# server robustness: shed accounting, expiry, breaker metrics
# ---------------------------------------------------------------------
class _GatedRuntime:
    """Executor gated on an event — deterministic queue pressure."""

    def __init__(self, name="gated", max_batch=2):
        self.name = name
        self.sample_shape = (2,)
        self.max_batch = max_batch
        self.plan = serving.plan_batch_buckets(max_batch)
        self.compiled = True
        self.gate = threading.Event()
        self.executed = 0

    def bucket_for(self, n):
        for b in self.plan:
            if n <= b:
                return b
        raise ValueError(n)

    def execute(self, batch):
        self.gate.wait(10.0)
        self.executed += int(np.asarray(batch).shape[0])
        return np.asarray(batch).sum(axis=-1)


def _counter_value(name, **labels):
    c = diag.metrics.counter(name, labels=labels or None)
    return c.value


def test_queue_full_shed_is_counted():
    rt = _GatedRuntime()
    srv = serving.ModelServer(queue_max=2, max_batch=2,
                              batch_deadline_ms=1,
                              default_deadline_ms=10_000)
    srv.add_model(rt)
    before = _counter_value("mxnet_serve_rejected_total",
                           reason="queue_full")
    x = np.ones((1, 2), dtype="float32")
    admitted, shed = [], 0
    for _ in range(7):
        try:
            admitted.append(srv.submit("gated", x))
        except serving.Rejected as e:
            assert e.reason == "queue_full"
            assert e.retry_after_s is not None and e.retry_after_s > 0
            shed += 1
    assert shed >= 3  # 7 offers vs <=2 riding + 2 queued
    rt.gate.set()
    for r in admitted:
        r.wait(10.0)
    after = _counter_value("mxnet_serve_rejected_total",
                          reason="queue_full")
    assert after - before == shed


def test_unknown_model_and_bad_input_shed():
    srv = serving.ModelServer(queue_max=2, max_batch=2)
    with pytest.raises(serving.Rejected) as ei:
        srv.submit("nope", np.ones((1, 2), dtype="float32"))
    assert ei.value.reason == "unknown_model"
    rt = _GatedRuntime("shapes")
    rt.gate.set()
    srv.add_model(rt)
    with pytest.raises(serving.Rejected) as ei:
        srv.submit("shapes", np.ones((1, 5), dtype="float32"))
    assert ei.value.reason == "bad_input"
    with pytest.raises(serving.Rejected) as ei:
        srv.submit("shapes", np.ones((9, 2), dtype="float32"))
    assert ei.value.reason == "too_large"


def test_expired_request_never_dispatched():
    rt = _GatedRuntime()
    srv = serving.ModelServer(queue_max=8, max_batch=2,
                              batch_deadline_ms=1,
                              default_deadline_ms=10_000)
    srv.add_model(rt)
    x = np.ones((1, 2), dtype="float32")
    blocker = srv.submit("gated", x)
    time.sleep(0.05)  # the batcher takes the blocker, wedges on gate
    victim = srv.submit("gated", x, deadline_ms=30)
    time.sleep(0.08)  # victim expires while QUEUED
    rt.gate.set()
    blocker.wait(10.0)
    with pytest.raises(serving.DeadlineExceeded):
        victim.wait(5.0)
    # the expired sample was never executed
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and rt.executed < 1:
        time.sleep(0.01)
    assert rt.executed == 1


def test_breaker_trip_flushes_queue_and_resets():
    class _Flaky(_GatedRuntime):
        def __init__(self):
            super().__init__("flaky2", max_batch=2)
            self.gate.set()
            self.fail = True

        def execute(self, batch):
            if self.fail:
                raise serving.ExecutorFailure("boom")
            return super().execute(batch)

    rt = _Flaky()
    srv = serving.ModelServer(queue_max=8, max_batch=2,
                              batch_deadline_ms=1,
                              default_deadline_ms=10_000,
                              breaker_n=2, breaker_reset_s=0.15)
    srv.add_model(rt)
    x = np.ones((1, 2), dtype="float32")
    for _ in range(2):
        r = srv.submit("flaky2", x)
        with pytest.raises(serving.ExecutorFailure):
            r.wait(10.0)
    deadline = time.monotonic() + 5.0
    while srv._get("flaky2").breaker.state() == "closed" and \
            time.monotonic() < deadline:
        time.sleep(0.005)
    assert srv._get("flaky2").breaker.state() in ("open", "half_open")
    with pytest.raises(serving.Rejected) as ei:
        srv.submit("flaky2", x)
    assert ei.value.reason == "breaker_open"
    assert ei.value.retry_after_s is not None
    # half-open probe after the reset window closes it again
    time.sleep(0.2)
    rt.fail = False
    probe = srv.submit("flaky2", x)
    probe.wait(10.0)
    assert srv._get("flaky2").breaker.state() == "closed"


def test_breaker_lost_probe_does_not_wedge():
    """A half-open probe that is shed at offer (or expires in the
    queue) must not leave the breaker fast-failing forever: an
    explicit abort releases the reservation, and the reservation
    itself times out after reset_s."""
    br = serving.CircuitBreaker(1, 0.05)
    assert br.on_failure() and br.state() == "open"
    time.sleep(0.06)
    assert br.admit() is True          # the probe reservation
    assert br.admit() is False         # concurrent submits fast-fail
    br.abort_probe()                   # probe was shed at offer
    assert br.admit() is True          # next submit may probe NOW
    time.sleep(0.06)                   # probe expired in queue instead
    assert br.admit() is True          # reservation timed out too
    br.on_success()
    assert br.state() == "closed"


def test_probes_ready_vs_live():
    rt = _GatedRuntime("probe2")
    rt.gate.set()
    srv = serving.ModelServer(queue_max=4, max_batch=2,
                              batch_deadline_ms=1)
    srv.add_model(rt)
    rep = srv.ready()
    assert rep["ready"] and srv.live()
    srv.drain(timeout_s=5.0)
    assert not srv.ready()["ready"]
    assert not srv.live()


# ---------------------------------------------------------------------
# e2e: chaos-slowed executors at 2x capacity — bounded p99 for admitted
# traffic, excess shed WITH accounting; drain-under-load loses nothing
# ---------------------------------------------------------------------
def _overloaded_server(monkeypatch, slow_ms=5, queue_max=32,
                       deadline_ms=2000):
    monkeypatch.setenv(
        "MXNET_CHAOS",
        "slow_request:model=demo,ms=%d,count=1000000" % slow_ms)
    chaos.reset()
    rt = serving.demo_runtime(max_batch=8)
    srv = serving.ModelServer(max_batch=8, queue_max=queue_max,
                              batch_deadline_ms=2,
                              default_deadline_ms=deadline_ms)
    srv.add_model(rt)
    return srv


def test_e2e_overload_bounded_p99_and_shed(monkeypatch):
    deadline_ms = 2000
    srv = _overloaded_server(monkeypatch, deadline_ms=deadline_ms)
    try:
        # calibrate capacity at a gentle rate, then offer ~2x
        calib = serving.run_load(srv, "demo", qps=100, duration_s=0.5)
        assert calib["ok"] > 0 and calib["hung"] == 0
        cap_qps = 8 / 0.007  # 8-sample buckets, ~(5+2)ms per batch
        before = _counter_value("mxnet_serve_rejected_total",
                               reason="queue_full")
        st = serving.run_load(srv, "demo", qps=2 * cap_qps,
                              duration_s=2.0)
        # accounting closes: every offered request is admitted or shed
        assert st["admitted"] + st["shed_total"] == st["offered"]
        assert st["hung"] == 0 and st["errors"] == 0
        # excess traffic WAS shed, and the shed counter accounts for it
        assert st["shed"].get("queue_full", 0) > 0
        after = _counter_value("mxnet_serve_rejected_total",
                              reason="queue_full")
        assert after - before >= st["shed"]["queue_full"]
        # admitted requests kept a bounded p99 under the deadline
        assert st["ok"] > 0
        assert st["p99_ms"] < deadline_ms, st
        assert chaos.injected_total("slow_request") > 0
    finally:
        chaos.reset()


def test_e2e_drain_under_load_loses_nothing(monkeypatch):
    srv = _overloaded_server(monkeypatch, slow_ms=5, queue_max=64,
                             deadline_ms=30_000)
    try:
        load = serving.BackgroundLoad(srv, "demo", qps=400,
                                      duration_s=3.0).start()
        time.sleep(0.6)  # mid-load: queue is non-empty
        rep = srv.drain(timeout_s=15.0)
        st = load.join(30.0)
        assert st is not None
        # drain completed every admitted in-flight request
        assert rep["drained"] and rep["left"] == 0, rep
        assert st["hung"] == 0, st
        assert st["ok"] == st["admitted"], st
        # offers arriving after the drain began were shed as draining
        assert st["shed"].get("draining", 0) > 0, st
    finally:
        chaos.reset()


def test_e2e_fail_execute_chaos_trips_breaker(monkeypatch):
    monkeypatch.setenv("MXNET_CHAOS",
                       "fail_execute:model=demo,count=1000000")
    chaos.reset()
    try:
        rt = serving.demo_runtime(max_batch=4)
        srv = serving.ModelServer(max_batch=4, queue_max=16,
                                  batch_deadline_ms=1,
                                  default_deadline_ms=5_000,
                                  breaker_n=3, breaker_reset_s=30.0)
        srv.add_model(rt)
        x = np.zeros((1, 16), dtype="float32")
        for _ in range(3):
            r = srv.submit("demo", x)
            with pytest.raises(serving.ExecutorFailure):
                r.wait(10.0)
        deadline = time.monotonic() + 5.0
        while srv._get("demo").breaker.state() == "closed" and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv._get("demo").breaker.state() == "open"
        with pytest.raises(serving.Rejected) as ei:
            srv.submit("demo", x)
        assert ei.value.reason == "breaker_open"
        assert chaos.injected_total("fail_execute") >= 3
    finally:
        chaos.reset()


# ---------------------------------------------------------------------
# SIGTERM drain: subprocess exits 83 with zero admitted requests lost
# ---------------------------------------------------------------------
def test_sigterm_drain_exits_83_and_completes_admitted(tmp_path):
    report = str(tmp_path / "drain_report.json")
    env = _child_env({
        "MXNET_CHAOS": "slow_request:model=demo,ms=5,count=1000000",
    })
    proc = subprocess.Popen(
        [sys.executable, _SERVE_WORKER, report],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=ROOT)
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
        time.sleep(0.8)  # let it admit a stream of requests
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == diag.EXIT_PREEMPTED, (rc, proc.stderr.read())
    with open(report) as f:
        rep = json.load(f)
    assert rep["drain"]["drained"] and rep["drain"]["left"] == 0, rep
    assert rep["admitted"] > 0
    # every admitted request completed before exit; none hung or lost
    assert rep["done"] == rep["admitted"], rep
    assert rep["ok"] == rep["admitted"], rep


# ---------------------------------------------------------------------
# HTTP front-end: status mapping is the shed contract made visible
# ---------------------------------------------------------------------
def test_http_roundtrip_and_probe_status():
    rt = serving.demo_runtime(max_batch=4)
    srv = serving.ModelServer(max_batch=4, queue_max=8,
                              batch_deadline_ms=1)
    srv.add_model(rt)
    fe = serving.HttpFrontend(srv, port=0)
    host, port = fe.start()
    base = "http://%s:%d" % (host, port)
    try:
        assert urllib.request.urlopen(base + "/healthz").status == 200
        assert urllib.request.urlopen(base + "/readyz").status == 200
        prom = urllib.request.urlopen(base + "/metrics").read().decode()
        assert not diag.validate_prom_text(prom)
        req = urllib.request.Request(
            base + "/v1/models/demo:predict",
            data=json.dumps({"instances": [[0.5] * 16]}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req)
        body = json.loads(resp.read())
        assert resp.status == 200 and len(body["predictions"][0]) == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/ghost:predict",
                data=b'{"instances": [[1.0]]}'))
        assert ei.value.code == 404
        # valid JSON that is not an object must be a clean 400, not a
        # dropped connection
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/demo:predict", data=b'[1, 2, 3]'))
        assert ei.value.code == 400
        srv.drain(timeout_s=5.0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz")
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/demo:predict",
                data=json.dumps({"instances": [[0.5] * 16]}).encode()))
        assert ei.value.code == 503  # draining
    finally:
        fe.stop()


# ---------------------------------------------------------------------
# serving metrics surface quantile gauges (the diagnostics satellite,
# observed end-to-end through real traffic)
# ---------------------------------------------------------------------
def test_serving_latency_quantiles_in_prom():
    rt = serving.demo_runtime(max_batch=4)
    srv = serving.ModelServer(max_batch=4, queue_max=8,
                              batch_deadline_ms=1)
    srv.add_model(rt)
    x = np.zeros((2, 16), dtype="float32")
    for _ in range(5):
        srv.predict("demo", x)
    text = diag.metrics.to_prom()
    assert not diag.validate_prom_text(text)
    assert "mxnet_serve_latency_seconds_p50" in text
    assert "mxnet_serve_latency_seconds_p99" in text
    # outcome counters carry the serving VERSION label (the reload
    # tentpole: a scraper can split error rates per model version)
    assert ('mxnet_serve_requests_total{model="demo",outcome="ok",'
            'version="v1"}') in text


# ---------------------------------------------------------------------
# live reload: hot swap, canary rollback, fail-closed (the tentpole)
# ---------------------------------------------------------------------
def _drive_until_terminal(srv, model, x, timeout_s=30.0):
    """Keep traffic flowing until the reload decision lands; returns
    (terminal_state, n_ok, n_failed) — the zero-drop accounting."""
    n_ok = n_failed = 0
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            srv.predict(model, x)
            n_ok += 1
        except Exception:
            n_failed += 1
        st = srv.reload_status(model)
        if st["state"] in ("promoted", "rolled_back", "failed"):
            return st, n_ok, n_failed
    return srv.reload_status(model), n_ok, n_failed


def test_reload_hot_swap_promotes_with_zero_drop(tmp_path):
    """A new version loads from a digest-verified checkpoint, warms in
    the background, canaries, promotes — and every request submitted
    during the swap is answered (zero admitted dropped)."""
    d = str(tmp_path / "v2ckpt")
    ckpt.save_checkpoint(d, 3, params=serving.demo_params(seed=9))
    rt = serving.demo_runtime(max_batch=4, seed=0)
    srv = serving.ModelServer(max_batch=4, queue_max=64,
                              batch_deadline_ms=1, canary_pct=50,
                              canary_min_n=4)
    srv.add_model(rt)
    x = np.random.RandomState(0).randn(1, 16).astype("float32")
    before = srv.predict("demo", x)[1]
    srv.reload("demo", d)
    st, n_ok, n_failed = _drive_until_terminal(srv, "demo", x)
    assert st["state"] == "promoted", st
    assert n_failed == 0 and n_ok > 0, (n_ok, n_failed)
    assert st["canary_stats"]["errors"] == 0
    # the server now answers from the NEW weights
    v2 = serving.demo_runtime(max_batch=4, seed=9)
    v2.compile(warmup=False)
    want = np.float64(np.asarray(v2.execute(x)[1]))
    got = np.float64(np.asarray(srv.predict("demo", x)[1]))
    assert np.allclose(got, want), "post-swap output is not v2's"
    assert not np.allclose(got, np.float64(np.asarray(before)))
    assert srv.stats()["demo"]["version"] == 2
    # reloads are counted by terminal outcome
    assert diag.metrics.counter(
        "mxnet_serve_reloads_total",
        labels={"model": "demo", "outcome": "promoted"}).value >= 1
    srv.drain(timeout_s=5.0)


def test_reload_bad_version_rolls_back_e2e(tmp_path, monkeypatch):
    """Acceptance e2e: chaos 'bad_version' makes every canary batch of
    the new version fail — the server auto-rolls-back with ZERO
    admitted requests dropped (failed canary batches re-execute on the
    stable version) and mxnet_serve_rollbacks_total increments."""
    d = str(tmp_path / "v2ckpt")
    ckpt.save_checkpoint(d, 3, params=serving.demo_params(seed=9))
    rt = serving.demo_runtime(max_batch=4, seed=0)
    srv = serving.ModelServer(max_batch=4, queue_max=64,
                              batch_deadline_ms=1, canary_pct=50,
                              canary_min_n=4)
    srv.add_model(rt)
    x = np.random.RandomState(1).randn(1, 16).astype("float32")
    stable_out = np.float64(np.asarray(srv.predict("demo", x)[1]))
    rb_before = diag.metrics.counter(
        "mxnet_serve_rollbacks_total", labels={"model": "demo"}).value
    monkeypatch.setenv("MXNET_CHAOS",
                       "bad_version:model=demo,count=100000")
    chaos.reset()
    try:
        srv.reload("demo", d)
        st, n_ok, n_failed = _drive_until_terminal(srv, "demo", x)
        injected = chaos.injected_total("bad_version")
    finally:
        monkeypatch.delenv("MXNET_CHAOS")
        chaos.reset()
    assert st["state"] == "rolled_back", st
    assert injected > 0, "the bad_version fault never fired"
    # zero admitted dropped: every request during the canary answered OK
    assert n_failed == 0 and n_ok > 0, (n_ok, n_failed)
    assert st["canary_stats"]["errors"] >= 4
    assert diag.metrics.counter(
        "mxnet_serve_rollbacks_total",
        labels={"model": "demo"}).value == rb_before + 1
    # stable version keeps serving, bit-identical to before the canary
    after = np.float64(np.asarray(srv.predict("demo", x)[1]))
    assert np.allclose(after, stable_out)
    assert srv.stats()["demo"]["version"] == 1
    assert srv.stats()["demo"]["canary_version"] is None
    srv.drain(timeout_s=5.0)


def test_reload_corrupt_checkpoint_fails_closed(tmp_path):
    """Integrity meets serving: a reload pointed at a corrupt
    checkpoint FAILS (naming the shard) and the stable version keeps
    serving untouched — the bad bytes never reach traffic."""
    d = str(tmp_path / "badckpt")
    ckpt.save_checkpoint(d, 3, params=serving.demo_params(seed=9))
    with open(ckpt.shard_path(d, 3, 0), "r+b") as f:
        f.seek(50)
        f.write(b"\x00\x01\x02\x03")
    rt = serving.demo_runtime(max_batch=4, seed=0)
    srv = serving.ModelServer(max_batch=4, queue_max=16,
                              batch_deadline_ms=1)
    srv.add_model(rt)
    x = np.zeros((1, 16), dtype="float32")
    st = srv.reload("demo", d, wait_s=30.0)
    assert st["state"] == "failed", st
    assert "rank0.ckpt" in str(st.get("error", "")), st
    assert srv.predict("demo", x)[0].shape == (1,)
    assert srv.stats()["demo"]["version"] == 1
    # a second reload attempt is allowed after a failed one
    assert srv.reload_status("demo")["state"] == "failed"
    srv.drain(timeout_s=5.0)


def test_reload_in_progress_rejected(tmp_path):
    d = str(tmp_path / "v2ckpt")
    ckpt.save_checkpoint(d, 3, params=serving.demo_params(seed=9))
    rt = serving.demo_runtime(max_batch=4, seed=0)
    srv = serving.ModelServer(max_batch=4, queue_max=16,
                              batch_deadline_ms=1, canary_pct=50,
                              canary_min_n=4)
    srv.add_model(rt)
    srv.reload("demo", d)  # no traffic -> sits in loading/canary
    with pytest.raises(serving.Rejected) as ei:
        srv.reload("demo", d)
    assert ei.value.reason == "reload_in_progress"
    # finish it so drain is clean
    x = np.zeros((1, 16), dtype="float32")
    st, _, _ = _drive_until_terminal(srv, "demo", x)
    assert st["state"] == "promoted"
    srv.drain(timeout_s=5.0)


def test_http_reload_route(tmp_path):
    """POST /v1/models/<name>:reload kicks the zero-downtime reload;
    the stats route exposes the reload state machine."""
    d = str(tmp_path / "v2ckpt")
    ckpt.save_checkpoint(d, 3, params=serving.demo_params(seed=9))
    rt = serving.demo_runtime(max_batch=4, seed=0)
    srv = serving.ModelServer(max_batch=4, queue_max=16,
                              batch_deadline_ms=1, canary_pct=0)
    srv.add_model(rt)
    fe = serving.HttpFrontend(srv, port=0)
    host, port = fe.start()
    base = "http://%s:%d" % (host, port)
    try:
        req = urllib.request.Request(
            base + "/v1/models/demo:reload",
            data=json.dumps({"directory": d, "wait_s": 30}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req)
        body = json.loads(resp.read())
        # canary_pct=0: promoted as soon as compiled+warm (no traffic
        # needed), waited to terminal -> 200
        assert resp.status == 200, body
        assert body["reload"]["state"] == "promoted", body
        stats = json.loads(urllib.request.urlopen(
            base + "/stats").read())
        assert stats["demo"]["version"] == 2
        assert stats["demo"]["reload"]["state"] == "promoted"
        # bad body -> 400; unknown model -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/demo:reload", data=b'{}'))
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/ghost:reload",
                data=json.dumps({"directory": d}).encode()))
        assert ei.value.code == 404
    finally:
        srv.drain(timeout_s=5.0)
        fe.stop()


# ---------------------------------------------------------------------
# elastic heartbeat coverage (ISSUE 15 satellite): the batcher loop
# beacons liveness, so a supervised server idling between requests is
# never falsely SIGKILLed by MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S
# ---------------------------------------------------------------------
def test_batcher_loop_touches_heartbeat(tmp_path, monkeypatch):
    hb_dir = str(tmp_path / "hb")
    monkeypatch.setenv("MXNET_ELASTIC_HEARTBEAT_DIR", hb_dir)
    # reset the rate limiter so the beacon fires for THIS dir
    monkeypatch.setattr(diag, "_hb_last", 0.0)
    monkeypatch.setattr(diag, "_hb_path", None)
    rt = serving.demo_runtime(max_batch=2)
    srv = serving.ModelServer(max_batch=2, queue_max=4)
    try:
        srv.add_model(rt)
        deadline = time.monotonic() + 5.0
        path = os.path.join(hb_dir, "hb_rank0")
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.05)  # no traffic at all — idling must beacon
        assert os.path.exists(path), os.listdir(hb_dir) \
            if os.path.isdir(hb_dir) else "no hb dir"
    finally:
        srv.drain(timeout_s=5.0)
