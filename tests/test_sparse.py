"""Sparse storage tests, modeled on the reference's
tests/python/unittest/test_sparse_ndarray.py and test_sparse_operator.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rand_dense(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    arr = rng.uniform(-1, 1, size=shape).astype(np.float32)
    mask = rng.uniform(size=shape) < density
    return (arr * mask).astype(np.float32)


# ---------------------------------------------------------------------------
# creation / round trips
# ---------------------------------------------------------------------------
def test_csr_roundtrip():
    dense = _rand_dense((7, 5))
    csr = sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    assert csr.shape == (7, 5)
    np.testing.assert_allclose(csr.asnumpy(), dense, rtol=1e-6)
    back = csr.tostype("default")
    assert back.stype == "default"
    np.testing.assert_allclose(back.asnumpy(), dense, rtol=1e-6)


def test_csr_from_parts():
    # 2x3: [[0,1,0],[2,0,3]]
    csr = sparse.csr_matrix(
        (np.array([1.0, 2.0, 3.0]), np.array([1, 0, 2]), np.array([0, 1, 3])),
        shape=(2, 3))
    np.testing.assert_allclose(csr.asnumpy(),
                               [[0, 1, 0], [2, 0, 3]], rtol=1e-6)
    np.testing.assert_array_equal(csr.indptr.asnumpy(), [0, 1, 3])
    np.testing.assert_array_equal(csr.indices.asnumpy(), [1, 0, 2])


def test_rsp_roundtrip():
    dense = np.zeros((6, 4), np.float32)
    dense[1] = 1.5
    dense[4] = -2.0
    rsp = sparse.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4])
    assert rsp.data.shape == (2, 4)
    np.testing.assert_allclose(rsp.asnumpy(), dense, rtol=1e-6)


def test_rsp_from_parts():
    rsp = sparse.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([0, 3])), shape=(5, 3))
    out = rsp.asnumpy()
    assert out[0].sum() == 3 and out[3].sum() == 3 and out.sum() == 6


def test_cast_storage():
    dense = nd.array(_rand_dense((4, 6)))
    for stype in ("csr", "row_sparse"):
        sp = sparse.cast_storage(dense, stype)
        assert sp.stype == stype
        np.testing.assert_allclose(sp.asnumpy(), dense.asnumpy(), rtol=1e-6)
        assert sparse.cast_storage(sp, stype) is sp
    assert dense.tostype("csr").stype == "csr"


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (3, 2))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    zc = sparse.zeros("csr", (3, 2))
    assert zc.stype == "csr" and zc.asnumpy().sum() == 0
    via_nd = nd.zeros((3, 2), stype="csr")
    assert via_nd.stype == "csr"


def test_dense_fallback_write():
    """Writing through the dense bridge invalidates + recompresses parts."""
    rsp = sparse.row_sparse_array(np.zeros((4, 2), np.float32))
    rsp[:] = np.ones((4, 2), np.float32)
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(rsp.asnumpy(), np.ones((4, 2)), rtol=1e-6)


# ---------------------------------------------------------------------------
# sparse compute
# ---------------------------------------------------------------------------
def test_retain():
    dense = np.zeros((6, 2), np.float32)
    dense[[1, 3, 5]] = [[1, 1], [3, 3], [5, 5]]
    rsp = sparse.row_sparse_array(dense)
    kept = sparse.retain(rsp, nd.array([1, 5]))
    np.testing.assert_array_equal(kept.indices.asnumpy(), [1, 5])
    expected = np.zeros_like(dense)
    expected[[1, 5]] = dense[[1, 5]]
    np.testing.assert_allclose(kept.asnumpy(), expected, rtol=1e-6)


@pytest.mark.parametrize("ta", [False, True])
def test_csr_dot(ta):
    lhs = _rand_dense((8, 5), 0.4, seed=1)
    rhs = np.random.RandomState(2).uniform(-1, 1, (8 if ta else 5, 3)).astype(np.float32)
    csr = sparse.csr_matrix(lhs)
    out = sparse.dot(csr, nd.array(rhs), transpose_a=ta)
    expected = (lhs.T if ta else lhs) @ rhs
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-4, atol=1e-5)


def test_rsp_dot():
    lhs = np.zeros((6, 4), np.float32)
    lhs[[0, 2]] = np.random.RandomState(3).uniform(-1, 1, (2, 4))
    rhs = np.random.RandomState(4).uniform(-1, 1, (4, 3)).astype(np.float32)
    out = sparse.dot(sparse.row_sparse_array(lhs), nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), lhs @ rhs, rtol=1e-4, atol=1e-5)


def test_dense_dot_csr():
    lhs = np.random.RandomState(5).uniform(-1, 1, (3, 4)).astype(np.float32)
    rhs = _rand_dense((4, 6), 0.4, seed=6)
    out = sparse.dot(nd.array(lhs), sparse.csr_matrix(rhs))
    np.testing.assert_allclose(out.asnumpy(), lhs @ rhs, rtol=1e-4, atol=1e-5)


def test_rsp_elemwise():
    a = np.zeros((5, 3), np.float32)
    b = np.zeros((5, 3), np.float32)
    a[[0, 2]] = 1.0
    b[[2, 4]] = 2.0
    ra, rb = sparse.row_sparse_array(a), sparse.row_sparse_array(b)
    s = sparse.add(ra, rb)
    assert s.stype == "row_sparse"
    np.testing.assert_allclose(s.asnumpy(), a + b, rtol=1e-6)
    np.testing.assert_array_equal(s.indices.asnumpy(), [0, 2, 4])
    np.testing.assert_allclose(sparse.subtract(ra, rb).asnumpy(), a - b, rtol=1e-6)
    np.testing.assert_allclose(sparse.multiply(ra, rb).asnumpy(), a * b, rtol=1e-6)


def test_square_sum():
    dense = _rand_dense((6, 4), 0.5, seed=7)
    rsp = sparse.row_sparse_array(dense)
    np.testing.assert_allclose(sparse.square_sum(rsp).asnumpy(),
                               (dense ** 2).sum(), rtol=1e-5)
    np.testing.assert_allclose(sparse.square_sum(rsp, axis=1).asnumpy(),
                               (dense ** 2).sum(axis=1), rtol=1e-5)


def test_dense_ops_on_sparse_fallback():
    """Dense ops read sparse inputs through the densify bridge."""
    dense = _rand_dense((4, 4), 0.5, seed=8)
    csr = sparse.csr_matrix(dense)
    out = nd.sum(csr)
    np.testing.assert_allclose(out.asnumpy(), dense.sum(), rtol=1e-5)


# ---------------------------------------------------------------------------
# optimizer lazy updates
# ---------------------------------------------------------------------------
def _run_opt(opt_name, touched_rows, steps=3, **opt_kw):
    shape = (8, 3)
    rng = np.random.RandomState(9)
    w0 = rng.uniform(-1, 1, shape).astype(np.float32)
    gd = rng.uniform(-1, 1, (len(touched_rows),) + shape[1:]).astype(np.float32)

    opt = mx.optimizer.create(opt_name, learning_rate=0.1, **opt_kw)
    w = nd.array(w0)
    state = opt.create_state(0, w)
    for _ in range(steps):
        grad = sparse.row_sparse_array((gd, np.asarray(touched_rows)), shape=shape)
        opt.update(0, w, grad, state)
    return w0, w.asnumpy()


@pytest.mark.parametrize("opt_name,kw", [
    ("sgd", {"momentum": 0.9}),
    ("sgd", {}),
    ("adam", {}),
    ("adagrad", {}),
])
def test_lazy_update_untouched_rows(opt_name, kw):
    touched = [1, 4, 6]
    w0, w1 = _run_opt(opt_name, touched, **kw)
    untouched = [r for r in range(8) if r not in touched]
    # lazy semantics: rows absent from the gradient are bit-identical
    np.testing.assert_array_equal(w0[untouched], w1[untouched])
    # touched rows moved
    assert np.abs(w1[touched] - w0[touched]).max() > 1e-4


def test_sparse_sgd_matches_dense_on_touched_rows():
    """With wd=0 the lazy row update equals the dense update on touched rows."""
    shape = (6, 2)
    rng = np.random.RandomState(11)
    w0 = rng.uniform(-1, 1, shape).astype(np.float32)
    g_rows = np.array([0, 3])
    gd = rng.uniform(-1, 1, (2, 2)).astype(np.float32)
    g_dense = np.zeros(shape, np.float32)
    g_dense[g_rows] = gd

    opt_s = mx.optimizer.create("sgd", learning_rate=0.2, momentum=0.9)
    opt_d = mx.optimizer.create("sgd", learning_rate=0.2, momentum=0.9)
    ws, wd_ = nd.array(w0), nd.array(w0)
    ss, sd = opt_s.create_state(0, ws), opt_d.create_state(0, wd_)
    for _ in range(3):
        opt_s.update(0, ws, sparse.row_sparse_array((gd, g_rows), shape=shape), ss)
        opt_d.update(0, wd_, nd.array(g_dense), sd)
    np.testing.assert_allclose(ws.asnumpy()[g_rows], wd_.asnumpy()[g_rows],
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# kvstore row_sparse
# ---------------------------------------------------------------------------
def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = nd.array(np.arange(12, dtype=np.float32).reshape(6, 2))
    kv.init(3, w)
    out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull(3, out=out, row_ids=nd.array([1, 4]))
    assert out.stype == "row_sparse"
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 4])
    expected = np.zeros((6, 2), np.float32)
    expected[[1, 4]] = w.asnumpy()[[1, 4]]
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-6)


def test_kvstore_rsp_push():
    kv = mx.kv.create("local")
    shape = (5, 2)
    kv.init("w", nd.zeros(shape))
    a = np.zeros(shape, np.float32); a[1] = 1.0
    b = np.zeros(shape, np.float32); b[3] = 2.0
    kv.push("w", [sparse.row_sparse_array(a), sparse.row_sparse_array(b)])
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), a + b, rtol=1e-6)


def test_libsvm_iter(tmp_path):
    """LibSVMIter parses libsvm text into CSR batches
    (ref: src/io/iter_libsvm.cc)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import sparse as sp

    p = tmp_path / "train.libsvm"
    p.write_text(
        "1 0:1.5 3:2.0\n"
        "0 1:3.0\n"
        "1 2:0.5 3:1.0\n"
        "0 0:2.0 1:1.0\n"
        "1 3:4.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,),
                          batch_size=2)
    batches = list(it)
    assert len(batches) == 3  # 5 rows, round-batch pads the last
    b0 = batches[0]
    assert isinstance(b0.data[0], sp.CSRNDArray)
    dense = b0.data[0].todense().asnumpy()
    np.testing.assert_allclose(dense[0], [1.5, 0, 0, 2.0])
    np.testing.assert_allclose(dense[1], [0, 3.0, 0, 0])
    np.testing.assert_allclose(b0.label[0].asnumpy(), [1, 0])
    assert batches[2].pad == 1
    # sparse dot straight off the iterator (the libsvm workflow)
    w = mx.nd.ones((4, 2))
    out = mx.nd.dot(b0.data[0], w)
    np.testing.assert_allclose(out.asnumpy()[0], [3.5, 3.5])
    it.reset()
    assert len(list(it)) == 3


def test_libsvm_iter_validation(tmp_path):
    import pytest

    import mxnet_tpu as mx

    bad = tmp_path / "bad.libsvm"
    bad.write_text("1 7:1.0\n")
    with pytest.raises(mx.MXNetError):
        mx.io.LibSVMIter(data_libsvm=str(bad), data_shape=(4,),
                         batch_size=1)
    data = tmp_path / "d.libsvm"
    data.write_text("1 0:1.0\n0 1:1.0\n")
    lab = tmp_path / "l.libsvm"
    lab.write_text("0 0:1.0\n")
    with pytest.raises(mx.MXNetError):
        mx.io.LibSVMIter(data_libsvm=str(data), data_shape=(4,),
                         label_libsvm=str(lab), batch_size=1)


# ---------------------------------------------------------------------------
# PR 19: hot-row wire accounting, sparse embedding VJP, sparse compression,
# clickstream iterator, recommender local train
# ---------------------------------------------------------------------------
def _bytes_counter(op):
    from mxnet_tpu import diagnostics as diag
    return diag.metrics.counter("mxnet_kvstore_bytes_total",
                                labels={"op": op})


def test_rsp_dense_roundtrip_via_tostype():
    dense = _rand_dense((9, 3), 0.4, seed=12)
    rsp = sparse.row_sparse_array(dense)
    back = rsp.tostype("default").tostype("row_sparse")
    assert back.stype == "row_sparse"
    np.testing.assert_allclose(back.asnumpy(), dense, rtol=1e-6)


def test_row_sparse_pull_error_paths():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((4, 2)))
    out = sparse.zeros("row_sparse", (4, 2))
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull("w", out=out)                 # no row_ids
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull("w", row_ids=nd.array([0]))   # no out
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull("missing", out=out, row_ids=nd.array([0]))


def test_row_sparse_pull_bytes_proportional_to_unique_rows():
    """The hot-row claim's counter arithmetic: a pull's wire bytes are
    unique_rows * (row payload + 8B id) — same rows from a 64-row and a
    4096-row table cost the SAME bytes (∝ rows touched, not vocab)."""
    kv = mx.kv.create("local")
    dim = 4
    kv.init("small", nd.zeros((64, dim)))
    kv.init("big", nd.zeros((4096, dim)))
    rows = nd.array([3, 9, 9, 17, 3])  # 3 unique after dedup
    ctr = _bytes_counter("row_sparse_pull")
    deltas = {}
    for key, vocab in (("small", 64), ("big", 4096)):
        out = sparse.zeros("row_sparse", (vocab, dim))
        base = ctr.value
        kv.row_sparse_pull(key, out=out, row_ids=rows)
        deltas[key] = ctr.value - base
    expected = 3 * (dim * 4 + 8)
    assert deltas["small"] == deltas["big"] == expected, deltas


def test_row_sparse_push_bytes_under_own_op_label():
    """An all-row-sparse push accounts under op=row_sparse_push (rows +
    indices payload only), leaving op=push untouched — dashboards can
    separate hot-row traffic from dense traffic."""
    kv = mx.kv.create("local")
    dim, vocab = 4, 1024
    kv.init("t", nd.zeros((vocab, dim)))
    g = sparse.row_sparse_array(
        (np.ones((2, dim), np.float32), np.array([5, 900])),
        shape=(vocab, dim))
    ctr_s, ctr_d = _bytes_counter("row_sparse_push"), _bytes_counter("push")
    bs, bd = ctr_s.value, ctr_d.value
    kv.push("t", g)
    idx_bytes = 2 * np.dtype(g.indices.dtype).itemsize
    assert ctr_s.value - bs == 2 * dim * 4 + idx_bytes
    assert ctr_d.value == bd


def test_sparse_embedding_grad_pins_dense_embedding():
    """_contrib_SparseEmbedding (row-sparse dedup+segment-sum VJP) must
    produce the SAME weight gradient as the dense Embedding op."""
    from mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    vocab, dim = 50, 6
    w_np = rng.randn(vocab, dim).astype(np.float32)
    ids_np = rng.randint(0, vocab, (8, 3)).astype(np.float32)
    head = rng.randn(8, 3, dim).astype(np.float32)
    grads = {}
    for op in ("Embedding", "_contrib_SparseEmbedding"):
        w = nd.array(w_np)
        w.attach_grad()
        with autograd.record():
            emb = getattr(nd, op)(nd.array(ids_np), w,
                                  input_dim=vocab, output_dim=dim)
            loss = nd.sum(emb * nd.array(head))
        loss.backward()
        grads[op] = w.grad.asnumpy()
    assert np.abs(grads["Embedding"]).sum() > 0
    np.testing.assert_allclose(grads["_contrib_SparseEmbedding"],
                               grads["Embedding"], rtol=1e-6, atol=1e-6)


def test_row_sparse_embedding_grad_matches_dense_scatter():
    import jax.numpy as jnp

    from mxnet_tpu.ops.extra import row_sparse_embedding_grad

    rng = np.random.RandomState(1)
    vocab, dim = 20, 3
    ids = rng.randint(0, vocab, (4, 5))
    cot = rng.randn(4, 5, dim).astype(np.float32)
    rows, vals = row_sparse_embedding_grad(jnp.asarray(ids),
                                           jnp.asarray(cot), vocab)
    rows, vals = np.asarray(rows), np.asarray(vals)
    dense = np.zeros((vocab, dim), np.float32)
    np.add.at(dense, ids.reshape(-1), cot.reshape(-1, dim))
    got = np.zeros((vocab, dim), np.float32)
    keep = rows < vocab   # fill slots carry the sentinel id == vocab
    np.add.at(got, rows[keep], vals[keep])
    np.testing.assert_allclose(got, dense, rtol=1e-5, atol=1e-6)


def test_compress_rows_error_feedback_follows_row():
    """2-bit sparse compression carries residual PER (key, row-id): a
    row's sub-threshold remainder waits for that row's next appearance
    — across batches with different row sets — not for a position."""
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression("2bit", threshold=0.5)
    dim = 4
    quarter = np.full((2, dim), 0.25, np.float32)
    # round 1, rows [1, 3]: 0.25 < t on every element -> all zeros emit
    codes, shape = gc.compress_rows("k", np.array([1, 3]), quarter)
    assert len(codes) == GradientCompression.wire_nbytes(2 * dim)
    np.testing.assert_array_equal(gc.decompress(codes, shape), 0.0)
    # round 2, rows [3, 5]: row 3's residual 0.25 + 0.25 = 0.5 emits;
    # row 5 is fresh and keeps accumulating
    codes, shape = gc.compress_rows("k", np.array([3, 5]), quarter)
    out = gc.decompress(codes, shape)
    np.testing.assert_array_equal(out[0], 0.5)
    np.testing.assert_array_equal(out[1], 0.0)
    # round 3, row 1 alone: its round-1 residual was still waiting
    codes, shape = gc.compress_rows("k", np.array([1]), quarter[:1])
    np.testing.assert_array_equal(gc.decompress(codes, shape)[0], 0.5)
    # residual is per key: the same row under another key starts clean
    codes, shape = gc.compress_rows("k2", np.array([1]), quarter[:1])
    np.testing.assert_array_equal(gc.decompress(codes, shape), 0.0)
    assert GradientCompression.rows_wire_nbytes(3, dim) == \
        3 * 8 + (3 * dim + 3) // 4


def test_clickstream_iter_determinism_and_sharding():
    from mxnet_tpu.recommender import ClickstreamIter

    kw = dict(batch_size=8, n_fields=4, vocab=1000, num_samples=64,
              seed=3)
    a, b = ClickstreamIter(**kw), ClickstreamIter(**kw)
    for _ in range(3):
        da, la, pa = a.next_raw()
        db, lb, pb = b.next_raw()
        assert isinstance(da[0], np.ndarray) and da[0].dtype == np.int32
        assert la[0].shape == (8,)
        np.testing.assert_array_equal(da[0], db[0])
        np.testing.assert_array_equal(la[0], lb[0])
        assert pa == pb == 0
    p0 = ClickstreamIter(num_parts=2, part_index=0, **kw)
    p1 = ClickstreamIter(num_parts=2, part_index=1, **kw)
    d0, _, _ = p0.next_raw()
    d1, _, _ = p1.next_raw()
    assert not np.array_equal(d0[0], d1[0])  # disjoint worker slices
    spec = p1.replay_spec()
    assert spec["kind"] == "clickstream_iter"
    assert spec["num_parts"] == 2 and spec["part_index"] == 1
    # replay: a fresh iter fast-forwarded n batches continues bitwise
    c = ClickstreamIter(**kw)
    c.skip_batches(2)
    a.reset()
    a.skip_batches(2)
    np.testing.assert_array_equal(c.next_raw()[0][0], a.next_raw()[0][0])


def test_clickstream_zipf_hotness():
    from mxnet_tpu.recommender import make_clickstream

    ids, clicks = make_clickstream(2048, 4, 10000, alpha=1.05, seed=0)
    assert ids.shape == (2048, 4) and ids.dtype == np.int32
    assert clicks.shape == (2048,)
    assert 0 < clicks.sum() < 2048   # both classes present (learnable)
    # the hot-row premise: within a 32-batch, repeats collapse well
    # below batch size (uniform draws from vocab 10k would be ~32)
    mean_uni = np.mean([np.unique(ids[i:i + 32, 0]).size
                        for i in range(0, 2048, 32)])
    assert mean_uni < 30, mean_uni
    # deterministic per seed
    ids2, clicks2 = make_clickstream(2048, 4, 10000, alpha=1.05, seed=0)
    np.testing.assert_array_equal(ids, ids2)
    np.testing.assert_array_equal(clicks, clicks2)


@pytest.mark.parametrize("lr", [0.05, 0.0])
def test_recommender_sparse_matches_dense_control(lr):
    """The tentpole numerics pin: the PS-sharded hot-row path (dedup,
    row_sparse_pull, sparse server SGD on touched rows) and the dense
    full-table control produce BITWISE-equal loss trajectories on the
    same clickstream — including the lr=0 frozen-parameter pin."""
    import mxnet_tpu.recommender as rec

    cfg = rec.RecommenderConfig(n_fields=3, vocab=500, embed_dim=4,
                                mlp_hidden=(8,))

    def run(sparse):
        it = rec.ClickstreamIter(batch_size=16, n_fields=3, vocab=500,
                                 num_samples=256, seed=1)
        kv = mx.kv.create("local")
        step = rec.RecommenderTrainStep(
            cfg, kv,
            optimizer=mx.optimizer.SGD(learning_rate=lr, momentum=0.0,
                                       wd=0.0),
            n_shards=3 if sparse else 1, seed=0, sparse=sparse)
        return step.fit(it, 8)

    ctr = _bytes_counter("row_sparse_pull")
    base = ctr.value
    s = run(True)
    assert ctr.value > base   # the sparse run fed the hot-row counter
    d = run(False)
    if lr == 0.0:
        # frozen parameters: the two forwards gather the same values,
        # so the pin is BITWISE
        np.testing.assert_array_equal(
            np.asarray(s["losses"], np.float64),
            np.asarray(d["losses"], np.float64))
    else:
        # under updates the segment-sum vs dense-scatter accumulation
        # ORDER may differ by an f32 ulp on duplicated ids — the pin is
        # tight but not bitwise (the lr=0 case above is)
        np.testing.assert_allclose(s["losses"], d["losses"],
                                   rtol=1e-6, atol=1e-7)
        assert np.mean(s["losses"][-3:]) < np.mean(s["losses"][:3])
    assert 0 < s["mean_unique_rows_per_batch"] <= 16 * 3
