"""SSD end-to-end: the contrib detection ops proven jointly in a real
train + mAP-eval loop (ref: example/ssd/ train/train_net.py +
evaluate/eval_metric.py; the reference's nightly SSD smoke).

Uses the runnable example itself (examples/ssd/train_ssd.py) at a
CI-sized configuration: MultiBoxPrior anchors over two feature scales,
MultiBoxTarget with hard negative mining in the loss, MultiBoxDetection
+ NMS into a VOC-mAP metric.  Asserts optimization progress (falling
loss) and detection quality signal (mAP above chance and improving)."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "examples", "ssd"))


def test_ssd_train_eval_loop():
    import mxnet_tpu as mx
    from train_ssd import train

    mx.random.seed(3)
    np.random.seed(3)
    net, anchors, hist = train(epochs=4, batch_size=16, lr=0.06,
                               image_size=40, train_n=128, val_n=48,
                               num_workers=0, log=False)
    losses = [h[0] for h in hist]
    maps = [h[1] for h in hist]
    assert losses[-1] < 0.6 * losses[0], losses
    assert maps[-1] > 0.05, maps
    assert maps[-1] >= 0.8 * maps[0], maps


def test_map_metric_exact_values():
    """mAP arithmetic pinned on a hand-computable case."""
    from eval_metric import MApMetric, VOC07MApMetric

    # one image, class 0: two GT boxes; three detections — the high-
    # score one hits, the mid misses, the low hits the second GT
    label = np.array([[[0, 0.0, 0.0, 0.2, 0.2],
                       [0, 0.5, 0.5, 0.8, 0.8],
                       [-1, 0, 0, 0, 0]]], np.float32)
    det = np.array([[[0, 0.9, 0.0, 0.0, 0.2, 0.2],
                     [0, 0.6, 0.3, 0.3, 0.4, 0.4],
                     [0, 0.3, 0.5, 0.5, 0.8, 0.8]]], np.float32)
    m = MApMetric(iou_thresh=0.5)
    m.update([label], [det])
    name, val = m.get()
    # precision/recall points: (1/1, 0.5), (1/2, 0.5), (2/3, 1.0)
    # integrated AP = 0.5*1.0 + 0.5*(2/3)
    np.testing.assert_allclose(val, 0.5 + 0.5 * (2.0 / 3.0), rtol=1e-6)

    v = VOC07MApMetric(iou_thresh=0.5)
    v.update([label], [det])
    _, val07 = v.get()
    # 11-point: recall>=t gets max precision beyond t
    want = (6 * 1.0 + 5 * (2.0 / 3.0)) / 11.0
    np.testing.assert_allclose(val07, want, rtol=1e-6)

    # a whole class never detected drags the mean down
    label2 = np.array([[[1, 0.1, 0.1, 0.3, 0.3],
                        [-1, 0, 0, 0, 0],
                        [-1, 0, 0, 0, 0]]], np.float32)
    det2 = np.zeros((1, 0, 6), np.float32)
    m.update([label2], [det2])
    _, val2 = m.get()
    np.testing.assert_allclose(val2, (0.5 + 0.5 * (2.0 / 3.0)) / 2,
                               rtol=1e-6)
