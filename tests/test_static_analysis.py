"""Static-analysis subsystem: jaxpr auditor (mxnet_tpu/analysis) +
mxlint (tools/mxlint.py) + the central env registry (mxnet_tpu/env.py).

Covers the ISSUE-6 acceptance contract: every seeded fixture violation
(rank-dependent collective order, undonated 100MB buffer, bf16->f32
upcast, host callback) is flagged; the REAL compiled paths
(FusedTrainStep.step / multi_step on the CPU mesh, Module.bulk_fit)
pass clean against the committed baseline; mxlint reports zero
unregistered MXNET_* env reads.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis, diagnostics, env, gluon
from mxnet_tpu.analysis import auditor, fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# seeded fixture violations -> the auditor must flag each
# ---------------------------------------------------------------------------
def test_fixture_rank_dependent_collective_order():
    traces = fixtures.rank_dependent_traces()
    found = auditor.check_collective_uniformity(traces, "fx")
    assert found and found[0].check == "collective-uniformity"
    assert found[0].severity == "error"
    # the divergence point is named, --health style
    assert "divergence" in found[0].message


def test_fixture_undonated_100mb_buffer():
    found, summary = auditor.check_donation(
        fixtures.undonated_lowered(), "fx")
    assert found and found[0].check == "donation"
    assert found[0].details["wasted_bytes"] >= fixtures.UNDONATED_BYTES
    assert summary["donated_bytes"] == 0
    # the donated twin is clean
    clean, summary2 = auditor.check_donation(
        fixtures.donated_lowered(), "fx")
    assert not clean
    assert summary2["donated_bytes"] >= fixtures.UNDONATED_BYTES


def test_fixture_bf16_upcast():
    found = auditor.check_dtype(fixtures.upcast_jaxpr(), "fx",
                                "bfloat16")
    assert found and found[0].check == "dtype"
    assert found[0].details["n_wide"] >= 1
    # an f32-declared path upcasts nothing by definition
    assert auditor.check_dtype(fixtures.upcast_jaxpr(), "fx",
                               "float32") == []


def test_fixture_host_callback_under_scan():
    found = auditor.check_host_sync(fixtures.host_sync_jaxpr(), "fx")
    assert found and found[0].check == "host-sync"
    assert found[0].details["prim"] == "pure_callback"


def test_fixture_noop_remat_flagged():
    """A DECLARED policy whose trace contains zero remat eqns is an
    error finding — the run would OOM exactly where remat was supposed
    to save it (wrong scope string / markerless model)."""
    found = auditor.check_remat_effectiveness(
        fixtures.noop_remat_jaxpr(), "fx", "stage")
    assert found and found[0].check == "remat-effectiveness"
    assert found[0].severity == "error"
    assert "no-op-remat" in found[0].details["fingerprint_key"]
    # policy none declares nothing — no finding to raise
    assert auditor.check_remat_effectiveness(
        fixtures.noop_remat_jaxpr(), "fx", "none") == []


def test_fixture_remat_twin_peak_drops():
    """The effective per-stage plan leaves checkpoint eqns in the trace
    AND measurably lowers the liveness walk's peak residual bytes vs
    its no-remat twin; a plan that changes nothing is flagged."""
    remat_jx, twin_jx = fixtures.remat_twin_jaxprs()
    assert auditor.count_remat_eqns(remat_jx) >= 3
    assert auditor.count_remat_eqns(twin_jx) == 0
    peak = auditor.peak_live_bytes(remat_jx)
    twin_peak = auditor.peak_live_bytes(twin_jx)
    assert peak < twin_peak, (peak, twin_peak)
    # the real plan passes the twin comparison...
    assert auditor.check_remat_effectiveness(
        remat_jx, "fx", "stage", twin_jaxpr=twin_jx) == []
    # ...and an ineffective one (remat "plan" == its own twin) does not
    found = auditor.check_remat_effectiveness(
        twin_jx, "fx", "stage", twin_jaxpr=twin_jx)
    assert found and found[0].severity == "error"


def test_audit_step_meta_carries_remat_evidence():
    """audit_step stamps n_remat_eqns + peak_live_bytes into the site
    meta so audit_recorded_steps reports remat evidence next to the
    collective/donation accounting."""
    fn, specs = fixtures.clean_step()
    _findings, meta = auditor.audit_step(fn, specs, site="fx.clean",
                                         compute_dtype="bfloat16")
    assert meta["n_remat_eqns"] == 0
    assert meta["peak_live_bytes"] > 0


def test_clean_fixture_passes_all_checks():
    fn, specs = fixtures.clean_step()
    findings, meta = auditor.audit_step(fn, specs, site="fx.clean",
                                        compute_dtype="bfloat16")
    assert findings == []
    assert meta["n_collectives"] >= 1
    assert meta["donation"]["donated_bytes"] > 0


def test_baseline_suppression_roundtrip():
    found, _ = auditor.check_donation(fixtures.undonated_lowered(),
                                      "fx")
    fp = found[0].fingerprint()
    new, suppressed = auditor.apply_baseline(found, {fp})
    assert new == [] and suppressed == found
    new2, suppressed2 = auditor.apply_baseline(found, set())
    assert new2 == found and suppressed2 == []


# ---------------------------------------------------------------------------
# real compiled paths on the CPU mesh
# ---------------------------------------------------------------------------
def _fused_step(dtype=None, n_dev=2):
    import jax

    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((n_dev,), ("dp",), jax.devices()[:n_dev])
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, dtype=dtype)
    X = mx.nd.array(np.random.uniform(size=(8, 16)).astype("float32"))
    y = mx.nd.array(np.random.randint(0, 10, 8).astype("float32"))
    return step, X, y


def test_fused_train_step_audits_clean():
    step, X, y = _fused_step()
    step(X, y)                      # compiles + records .step
    step.run_steps(X, y, steps=2)   # compiles + records multi_step_same
    assert step.bucketed
    names = ["FusedTrainStep.step", "FusedTrainStep.multi_step_same[k=2]"]
    report = auditor.audit_recorded_steps(names=names)
    assert report.n_findings == 0, report.summary()
    assert set(names) <= set(report.sites)
    for name in names:
        meta = report.sites[name]
        assert "audit_error" not in meta, meta
        # bucketed build: the gradient psum(s) + the loss pmean
        assert meta["n_collectives"] >= 2
        assert meta["donation"]["donated_bytes"] > 0


def test_fused_train_step_bf16_dtype_clean():
    step, X, y = _fused_step(dtype="bfloat16")
    step(X, y)
    report = auditor.audit_recorded_steps(names=["FusedTrainStep.step"])
    assert report.n_findings == 0, report.summary()


def test_bucket_plan_embedded_in_traced_schedule():
    step, X, y = _fused_step()
    step(X, y)
    plan = diagnostics.bucket_plan()
    assert plan and plan["n_buckets"] >= 1
    fn, specs, _meta = diagnostics.recorded_steps()["FusedTrainStep.step"]
    import jax

    jaxpr = jax.make_jaxpr(getattr(fn, "_fn", fn))(*specs)
    assert auditor.check_bucket_plan(jaxpr, plan,
                                     "FusedTrainStep.step") == []
    # a plan the program does NOT implement is flagged
    fake = dict(plan)
    fake["buckets"] = [{"bucket": 0, "n_grads": 1,
                        "bytes": 123456789, "dtype": "float32"}]
    bad = auditor.check_bucket_plan(jaxpr, fake, "FusedTrainStep.step")
    assert bad and bad[0].check == "collective-uniformity"


def test_bulk_fit_audits_clean():
    from mxnet_tpu import engine

    x = mx.sym.Variable("data")
    x = mx.sym.FullyConnected(x, num_hidden=16, name="fc1")
    out = mx.sym.SoftmaxOutput(x, name="softmax")
    X = np.random.rand(32, 8).astype(np.float32)
    y = (X.sum(axis=1) > 4.0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8,
                           label_name="softmax_label")
    mod = mx.mod.Module(out)
    prev = engine.set_bulk_size(4)
    try:
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.1),),
                initializer=mx.init.Xavier())
    finally:
        engine.set_bulk_size(prev)
    assert "Module.bulk_fit" in diagnostics.recorded_steps(), \
        "bulk path did not record (fell back per-batch?)"
    report = auditor.audit_recorded_steps(names=["Module.bulk_fit"])
    assert report.n_findings == 0, report.summary()
    meta = report.sites["Module.bulk_fit"]
    assert "audit_error" not in meta, meta
    # params + optimizer state + the K-batch stack are all donated
    assert meta["donation"]["donated_bytes"] > 0


def test_run_steps_donation_never_consumes_caller_batch():
    step, X, y = _fused_step()
    step.run_steps(X, y, steps=2)
    step.run_steps(X, y, steps=2)   # same NDArrays again
    Xk = mx.nd.array(np.random.uniform(size=(2, 8, 16))
                     .astype("float32"))
    yk = mx.nd.array(np.random.randint(0, 10, (2, 8))
                     .astype("float32"))
    step.run_steps(Xk, yk)
    step.run_steps(Xk, yk)
    # the caller's buffers survived every donated dispatch
    assert X.asnumpy().shape == (8, 16)
    assert Xk.asnumpy().shape == (2, 8, 16)


# ---------------------------------------------------------------------------
# CLI gates (the tier-1 wiring)
# ---------------------------------------------------------------------------
def _run(args, **env_over):
    env_full = dict(os.environ, JAX_PLATFORMS="cpu", **env_over)
    return subprocess.run([sys.executable] + args, cwd=REPO,
                          capture_output=True, text=True, timeout=300,
                          env=env_full)


def test_analysis_self_test_cli():
    r = _run(["-m", "mxnet_tpu.analysis", "--self-test"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "self-test OK" in r.stdout


def test_mxlint_self_test_cli():
    r = _run(["-m", "tools.mxlint", "--self-test"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_mxlint_repo_clean():
    """Zero NEW findings over mxnet_tpu/ — in particular zero
    unregistered MXNET_* env reads (the registry acceptance
    criterion)."""
    out_json = os.path.join(REPO, ".mxlint_ci.json")
    try:
        r = _run(["-m", "tools.mxlint", "--json", out_json])
        assert r.returncode == 0, r.stdout + r.stderr
        data = json.load(open(out_json))
        assert data["n_findings"] == 0
        assert not [f for f in data["findings"]
                    if f["code"] in ("MXL001", "MXL005")]
    finally:
        if os.path.exists(out_json):
            os.remove(out_json)


# ---------------------------------------------------------------------------
# env registry
# ---------------------------------------------------------------------------
def test_env_registry_typed_accessors(monkeypatch):
    assert env.is_registered("MXNET_KVSTORE_BUCKET_BYTES")
    monkeypatch.delenv("MXNET_KVSTORE_BUCKET_BYTES", raising=False)
    assert env.get_int("MXNET_KVSTORE_BUCKET_BYTES") == 4 * 1024 * 1024
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "1024")
    assert env.get_int("MXNET_KVSTORE_BUCKET_BYTES") == 1024
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "junk")
    assert env.get_int("MXNET_KVSTORE_BUCKET_BYTES") == 4 * 1024 * 1024
    for spelling, want in (("0", False), ("off", False), ("No", False),
                           ("1", True), ("yes", True), ("ON", True)):
        monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", spelling)
        assert env.get_bool("MXNET_BACKWARD_DO_MIRROR") is want, spelling


def test_env_registry_rejects_unregistered():
    with pytest.raises(KeyError):
        env.get_str("MXNET_NOT_A_REAL_KNOB")
    with pytest.raises(KeyError):
        env.get_int("MXNET_ALSO_NOT_REAL")


def test_env_registry_describe_lists_every_knob():
    desc = env.describe()
    for name in ("MXNET_KVSTORE_BUCKET_BYTES", "MXNET_METRICS_FILE",
                 "MXNET_PROFILER_AUTOSTART"):
        assert name in desc


def test_registered_call_sites_honor_env(monkeypatch):
    from mxnet_tpu import remat
    from mxnet_tpu.parallel import buckets

    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert remat.mirror_enabled()
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "no")
    assert not remat.mirror_enabled()
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "0")
    assert buckets.bucket_cap_bytes() == 0
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_CHAIN", "false")
    assert not buckets.chain_enabled()


def test_engine_bulk_env_read_is_lazy():
    """MXNET_MODULE_BULK_SIZE set AFTER import must still be honored —
    the import-time read mxlint flags (MXL005) was a real bug for
    launchers that inject env per worker post-import."""
    code = ("import mxnet_tpu.engine as e; import os; "
            "os.environ['MXNET_MODULE_BULK_SIZE'] = '7'; "
            "assert e.fit_bulk_size() == 7, e.fit_bulk_size(); "
            "print('lazy-ok')")
    r = _run(["-c", code])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lazy-ok" in r.stdout
