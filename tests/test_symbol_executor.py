"""Symbol composition / inference / executor tests (modelled on
tests/python/unittest/test_symbol.py, test_executor.py, test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=16)
    net = sym.Activation(data=net, name="relu1", act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=3)
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_list_arguments_outputs():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label",
    ]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 20))
    assert arg_shapes == [(8, 20), (16, 20), (16,), (3, 16), (3,), (8,)]
    assert out_shapes == [(8, 3)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = sym.Variable("data")
    net = sym.Convolution(data=data, name="conv", kernel=(3, 3), num_filter=8,
                          pad=(1, 1))
    net = sym.BatchNorm(data=net, name="bn")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 16, 16))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (8, 3, 3, 3)
    assert d["bn_gamma"] == (8,)
    assert aux_shapes == [(8,), (8,)]
    assert out_shapes == [(2, 8, 16, 16)]
    assert net.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_infer_type():
    net = _mlp()
    arg_types, out_types, aux_types = net.infer_type(data=np.float32)
    assert out_types[0] == np.float32


def test_symbol_compose_arithmetic():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2 - a / b
    ex = c.bind(ctx=mx.cpu(), args={"a": nd.array([4.0]), "b": nd.array([2.0])})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), [(4 + 2) * 2 - 2.0])


def test_group_and_internals():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data=data, name="fc1", num_hidden=4)
    act = sym.Activation(data=fc1, name="act", act_type="relu")
    grouped = sym.Group([fc1, act])
    assert len(grouped.list_outputs()) == 2
    internals = act.get_internals()
    assert "fc1_output" in internals.list_outputs()


def test_executor_forward_backward():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 10))
    for name, arr in ex.arg_dict.items():
        if name.endswith("weight"):
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape).astype("float32")
    ex.arg_dict["data"][:] = np.random.rand(4, 10).astype("float32")
    ex.arg_dict["softmax_label"][:] = np.array([0, 1, 2, 0], dtype="float32")
    out = ex.forward(is_train=True)
    assert out[0].shape == (4, 3)
    np.testing.assert_allclose(out[0].asnumpy().sum(1), 1.0, rtol=1e-5)
    ex.backward()
    assert float(np.abs(ex.grad_dict["fc1_weight"].asnumpy()).sum()) > 0
    # label gets no grad buffer by default req
    assert ex.grad_dict["softmax_label"] is not None or True


def test_executor_symbolic_grads_match_autograd():
    np.random.seed(0)
    X = np.random.rand(8, 20).astype("float32")
    y = np.random.randint(0, 3, 8).astype("float32")
    w1 = np.random.uniform(-0.3, 0.3, (16, 20)).astype("float32")
    w2 = np.random.uniform(-0.3, 0.3, (3, 16)).astype("float32")

    aw1, aw2 = nd.array(w1), nd.array(w2)
    aw1.attach_grad(); aw2.attach_grad()
    with autograd.record():
        h = nd.Activation(nd.FullyConnected(nd.array(X), aw1, no_bias=True,
                                            num_hidden=16), act_type="relu")
        out = nd.SoftmaxOutput(nd.FullyConnected(h, aw2, no_bias=True,
                                                 num_hidden=3), nd.array(y))
    out.backward()

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, name="fc1", num_hidden=16, no_bias=True)
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, name="fc2", num_hidden=3, no_bias=True)
    net = sym.SoftmaxOutput(data=net, name="softmax")
    ex = net.simple_bind(ctx=mx.cpu(), data=(8, 20))
    ex.arg_dict["fc1_weight"][:] = w1
    ex.arg_dict["fc2_weight"][:] = w2
    ex.arg_dict["data"][:] = X
    ex.arg_dict["softmax_label"][:] = y
    ex.run_train_step()
    np.testing.assert_allclose(ex.grad_dict["fc1_weight"].asnumpy(),
                               aw1.grad.asnumpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ex.grad_dict["fc2_weight"].asnumpy(),
                               aw2.grad.asnumpy(), rtol=1e-4, atol=1e-5)


def test_executor_batchnorm_aux_update():
    data = sym.Variable("data")
    net = sym.BatchNorm(data=data, name="bn", fix_gamma=False, momentum=0.5)
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 3))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.rand(4, 3).astype("float32") * 3
    ex.forward(is_train=True, data=x)
    expect_mm = 0.5 * x.mean(0)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               expect_mm, rtol=1e-4, atol=1e-5)
    # eval mode does not touch aux
    mm = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=False, data=x)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(), mm)


def test_symbol_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    a1, o1, _ = net.infer_shape(data=(4, 10))
    a2, o2, _ = net2.infer_shape(data=(4, 10))
    assert a1 == a2 and o1 == o2


def test_attr_scope_ctx_group():
    with sym.AttrScope(ctx_group="dev1"):
        a = sym.Variable("a")
        fc = sym.FullyConnected(data=a, name="fc", num_hidden=2)
    assert fc.attr("ctx_group") == "dev1"


def test_multi_output_slice_channel():
    data = sym.Variable("data")
    parts = sym.SliceChannel(data=data, num_outputs=3, axis=1, name="split")
    assert len(parts.list_outputs()) == 3
    p0 = parts[0]
    ex = p0.bind(ctx=mx.cpu(), args={"data": nd.array(np.arange(12, dtype="float32").reshape(2, 6))})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), [[0, 1], [6, 7]])
