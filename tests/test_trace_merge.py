"""tools/merge_traces.py — multi-worker trace merging (fast tier-1).

Two synthetic rank dumps (the exact shape mxnet_tpu.profiler.dump
writes for ranks in a multi-worker run) must merge into one valid
chrome trace with events remapped onto per-rank pids.
"""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import merge_traces  # noqa: E402

_TOOL = os.path.join(os.path.dirname(__file__), "..", "tools",
                     "merge_traces.py")


def _rank_dump(tmp_path, rank, extra_events=()):
    payload = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
         "args": {"name": "rank %d" % rank}},
        {"name": "dot", "cat": "operator", "ph": "X", "ts": 5.0 + rank,
         "dur": 2.0, "pid": rank, "tid": 0},
        {"name": "KVStore::Push", "cat": "comms", "ph": "X", "ts": 9.0,
         "dur": 1.5, "pid": rank, "tid": 0, "args": {"bytes": 256}},
    ] + list(extra_events), "displayTimeUnit": "ms"}
    path = str(tmp_path / ("profile_rank%d.json" % rank))
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def test_merge_two_rank_dumps(tmp_path):
    p0 = _rank_dump(tmp_path, 0)
    p1 = _rank_dump(tmp_path, 1)
    out = str(tmp_path / "merged.json")
    merge_traces.merge_files([p0, p1], out)
    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    # every event landed on its rank's pid
    assert sorted({e["pid"] for e in events}) == [0, 1]
    for rank in (0, 1):
        lane = [e for e in events if e["pid"] == rank]
        names = [e["name"] for e in lane]
        assert names.count("dot") == 1
        assert names.count("KVStore::Push") == 1
        labels = [e["args"]["name"] for e in lane
                  if e.get("ph") == "M" and e["name"] == "process_name"]
        assert labels == ["rank %d" % rank]


def test_merge_remaps_stale_pids(tmp_path):
    """Events dumped with pid=0 by every rank (single-process-style
    dumps) must still split into distinct lanes by filename rank."""
    paths = []
    for rank in (0, 1):
        payload = {"traceEvents": [
            {"name": "op", "cat": "operator", "ph": "X", "ts": 1.0,
             "dur": 1.0, "pid": 0, "tid": 0}]}
        p = str(tmp_path / ("profile_rank%d.json" % rank))
        with open(p, "w") as f:
            json.dump(payload, f)
        paths.append(p)
    out = str(tmp_path / "m.json")
    merge_traces.merge_files(paths, out)
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    ops = [e for e in events if e["name"] == "op"]
    assert sorted(e["pid"] for e in ops) == [0, 1]


def test_cli_self_test_and_executable():
    assert os.access(_TOOL, os.X_OK), "merge_traces.py must be executable"
    res = subprocess.run([sys.executable, _TOOL, "--self-test"],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert "OK" in res.stdout


def test_cli_merge(tmp_path):
    p0 = _rank_dump(tmp_path, 0)
    p1 = _rank_dump(tmp_path, 1)
    out = str(tmp_path / "cli_merged.json")
    res = subprocess.run(
        [sys.executable, _TOOL, p0, p1, "-o", out],
        capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    with open(out) as f:
        trace = json.load(f)
    assert sorted({e["pid"] for e in trace["traceEvents"]}) == [0, 1]
