"""Measured device timeline (ISSUE 16): traceview capture ->
attribution -> autotune feedback.

* fixture-trace golden attribution + the committed self-test CLI
  (``python -m mxnet_tpu.traceview --self-test`` is tier-1 here);
* a LIVE dp=2 CPU-mesh ``FusedTrainStep`` capture cross-checked
  against the stamped bucket plan (scope-exact bucket map via the
  ``mxbkt<k>`` named scopes in the xplane sidecar);
* ``from_trace()`` -> ``tune()`` roundtrip pinning the acceptance
  criterion: a tuned plan built from a real captured trace carries
  ``assumptions.bandwidth_source == "trace"`` and measured per-bucket
  occupancy in its score block;
* cross-rank phase-skew health naming the slow rank, with
  chaos-injected stalls labeled instead of misattributed;
* mxlint MXL009 (direct ``jax.profiler`` use outside traceview/) and
  ``MXNET_TRACE_*`` env-registry drift;
* the regenerated OVERLAP_MEASURED.json v2 contract (device_timeline
  measurement + legacy schedule-walk labeled ``source=simulated``).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURE = os.path.join(ROOT, "mxnet_tpu", "traceview",
                       "fixture_trace.json")


def _import_tool(name):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------
# fixture golden attribution + the committed self-test CLI
# ---------------------------------------------------------------------
def test_fixture_golden_attribution():
    from mxnet_tpu.traceview import parse

    with open(FIXTURE) as f:
        fx = json.load(f)
    s = parse.attribute(fx["trace"], plan_meta=fx["plan_meta"],
                        workload="fixture")
    g = fx["golden"]
    assert s["format"] == parse.SUMMARY_FORMAT
    assert s["steps"]["n"] == g["n_steps"]
    assert s["plan_match"] is True
    for phase, want in g["phases_mean_s"].items():
        got = s["phases"][phase]["mean_s"]
        assert got == pytest.approx(want, rel=1e-6), phase
    assert s["overlap"]["overlap_frac"] == \
        pytest.approx(g["overlap_frac"], rel=1e-6)
    assert s["overlap"]["source"] == "trace"
    assert [b["bucket"] for b in s["buckets"]] == \
        [b["bucket"] for b in g["buckets"]]
    for got, want in zip(s["buckets"], g["buckets"]):
        assert got["occupancy"] == pytest.approx(want["occupancy"],
                                                 rel=1e-6)
        assert got["measured_GBps"] == \
            pytest.approx(want["measured_GBps"], rel=1e-6)


def test_traceview_self_test_cli():
    """The committed offline check the CI wires in: parser +
    attribution over the fixture and the synthetic CPU lanes."""
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.traceview", "--self-test"],
        cwd=ROOT, env=dict(os.environ), capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "traceview self-test OK" in proc.stdout, proc.stdout


# ---------------------------------------------------------------------
# live capture on the dp=2 CPU mesh (shared across the tests below)
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def live_capture(tmp_path_factory):
    """Arm the env-gated tracer, run a bucketed FusedTrainStep on a
    dp=2 CPU mesh, return (summary, summary_path).  A small bucket cap
    forces a multi-bucket plan so the scope-exact bucket map actually
    has something to prove."""
    import jax

    from mxnet_tpu import traceview
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    trace_dir = str(tmp_path_factory.mktemp("traceview_live"))
    knobs = {"MXNET_TRACE_DIR": trace_dir, "MXNET_TRACE_STEPS": "2",
             "MXNET_KVSTORE_BUCKET_BYTES": "1024"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    traceview.reset()
    try:
        mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(10))
        net.initialize(mx.init.Xavier())
        step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh)
        X = mx.nd.array(np.random.RandomState(0)
                        .uniform(size=(8, 16)).astype("float32"))
        y = mx.nd.array((np.arange(8) % 10).astype("float32"))
        for _ in range(4):        # 1 warmup + 2 windows + margin
            step(X, y)
        summary = traceview.last_summary()
        path = traceview.last_summary_path()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        traceview.reset()
    assert summary is not None, "armed tracer produced no summary"
    return summary, path


def test_live_capture_matches_bucket_plan(live_capture):
    """Bucket-plan cross-check: the capture's collective attribution
    must name exactly the stamped plan's buckets, via the mxbkt scope
    metadata (not the issue-order guess) — BN-stat psums or the loss
    pmean must never masquerade as gradient buckets."""
    summary, path = live_capture
    assert summary["format"] == "mxnet-tpu-traceview-summary"
    assert summary["bucket_map"] == "scope", summary["bucket_map"]
    assert summary["plan_match"] is True
    plan = summary["bucket_plan"]
    assert plan and plan["n_buckets"] >= 2, plan
    assert [b["bucket"] for b in summary["buckets"]] == \
        list(range(plan["n_buckets"]))
    assert summary["steps"]["n"] == 2
    for b in summary["buckets"]:
        assert b["device_s_per_step"] > 0.0, b
        assert 0.0 <= b["occupancy"] <= 1.0, b
        assert b["injected_stall"] is False, b
    # phase breakdown present and sane on the serial CPU executor
    for phase in ("h2d", "forward", "backward", "bucket_reduce",
                  "optimizer", "d2h"):
        assert phase in summary["phases"], summary["phases"].keys()
    assert summary["phases"]["bucket_reduce"]["mean_s"] > 0.0
    assert summary["overlap"]["source"] == "trace"
    assert 0.0 <= summary["overlap"]["overlap_frac"] <= 1.0
    # the summary landed on disk next to the trace
    assert path and os.path.exists(path)
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["bucket_map"] == "scope"
    assert on_disk["capture"]["warmup_skipped"] == 1
    assert on_disk["capture"]["trace_path"]


def test_live_capture_feeds_phase_metrics(live_capture):
    from mxnet_tpu import diagnostics as diag

    prom = diag.metrics.to_prom()
    assert "mxnet_step_phase_seconds" in prom
    assert 'phase="bucket_reduce"' in prom


def test_from_trace_tune_roundtrip(live_capture, tmp_path):
    """Acceptance pin: a tuned plan produced from a REAL captured
    trace records bandwidth_source="trace" and carries the measured
    per-bucket occupancy in its score block."""
    from mxnet_tpu.autotune import search, timing

    summary, path = live_capture
    model = timing.from_trace(summary, path=path)
    assert model.step_time_s and model.step_time_s > 0
    assert model.measured_GBps and model.measured_GBps > 0
    assert model.source["kind"] == "trace"
    plan = search.tune(model, chips=8)
    assert plan["assumptions"]["bandwidth_source"] == "trace"
    measured = plan["score"]["measured"]
    assert measured["source"] == "trace"
    assert 0.0 <= measured["overlap_frac"] <= 1.0
    occ = measured["bucket_occupancy"]
    assert len(occ) == summary["bucket_plan"]["n_buckets"]
    assert all(r["occupancy"] is not None for r in occ), occ
    # the tuned-plan JSON round-trips with the provenance intact
    out = tmp_path / "tuned_plan.json"
    out.write_text(json.dumps(plan, indent=1))
    back = json.loads(out.read_text())
    assert back["assumptions"]["bandwidth_source"] == "trace"
    assert back["score"]["measured"]["bucket_occupancy"] == occ
    # the content-sniffing loader accepts the on-disk summary too
    model2 = timing.load_any(path)
    assert model2.source["kind"] == "trace"


# ---------------------------------------------------------------------
# cross-rank phase-skew health (tools/merge_traces --health)
# ---------------------------------------------------------------------
def _tv_summary(rank, slow=1.0, injected=0):
    return {
        "format": "mxnet-tpu-traceview-summary", "version": 1,
        "rank": rank, "workload": "FusedTrainStep",
        "steps": {"n": 3, "mean_s": 0.01},
        "phases": {"backward": {"mean_s": 0.004},
                   "bucket_reduce": {"mean_s": 0.001 * slow}},
        "buckets": [{"bucket": b,
                     "device_s_per_step": 0.0002 * (slow if b == 5
                                                    else 1.0)}
                    for b in range(6)],
        "injected": {"events": injected,
                     "kinds": ["delay_collective"] if injected else []},
    }


def test_phase_skew_names_slow_rank():
    mt = _import_tool("merge_traces")
    tvs = {r: _tv_summary(r, slow=2.1 if r == 2 else 1.0)
           for r in range(3)}
    skew = mt.analyze_phase_skew(tvs)
    assert skew["detected"] is True
    assert {(f["kind"], f.get("bucket"), f["rank"])
            for f in skew["findings"]} >= {("bucket", 5, 2)}
    assert all(f["rank"] == 2 and not f["injected"]
               for f in skew["findings"])
    text = "\n".join(mt.format_health(
        mt.health_report({}, {}, traceviews=tvs)))
    assert "rank 2 spends 2.1x fleet-median in bucket 5 reduce" in text


def test_injected_stall_never_flips_health_verdict():
    """Satellite (a): the chaos tag is the deterministic signal — the
    same 2.1x skew reads INJECTED STALL, not straggler, and the
    verdict stays green with zero timing heuristics involved."""
    mt = _import_tool("merge_traces")
    tvs = {r: _tv_summary(r, slow=2.1 if r == 2 else 1.0,
                          injected=3 if r == 2 else 0)
           for r in range(3)}
    skew = mt.analyze_phase_skew(tvs)
    assert skew["findings"] and skew["detected"] is False
    assert skew["injected_ranks"] == [2]
    text = "\n".join(mt.format_health(
        mt.health_report({}, {}, traceviews=tvs)))
    assert "INJECTED STALL (chaos): rank 2" in text
    assert "not a hardware straggler" in text


def test_chaos_injection_tags_flight_entry_and_summary(monkeypatch):
    """delay_collective -> flight entry injected=true -> traceview
    summary injected block + per-bucket injected_stall."""
    from mxnet_tpu import chaos
    from mxnet_tpu import diagnostics as diag
    from mxnet_tpu.traceview import parse

    monkeypatch.setenv("MXNET_CHAOS", "delay_collective:op=push,ms=1")
    chaos.reset()
    try:
        seq = diag.record_start("push", keys=["w0"], bucket=1,
                                nbytes=64, dtype="float32")
        diag.record_complete(seq)
        _hdr, entries = diag.recorder.snapshot()
        tagged = [e for e in entries if e.get("injected")]
        assert tagged and tagged[-1]["injected_kind"] == \
            "delay_collective", entries[-3:]
        assert chaos.injected_total("delay_collective") == 1
    finally:
        chaos.reset()
    # the tag rides attribution into the summary + bucket rows
    with open(FIXTURE) as f:
        fx = json.load(f)
    s = parse.attribute(
        fx["trace"], plan_meta=fx["plan_meta"],
        flight_entries=[{"op": "bucket_reduce", "seq": 0, "bucket": 0},
                        {"op": "bucket_reduce", "seq": 1, "bucket": 1,
                         "injected": True,
                         "injected_kind": "delay_collective"}])
    assert s["injected"] == {"events": 1, "kinds": ["delay_collective"]}
    assert s["buckets"][1]["injected_stall"] is True
    assert s["buckets"][0]["injected_stall"] is False


# ---------------------------------------------------------------------
# mxlint MXL009: jax.profiler is traceview's monopoly
# ---------------------------------------------------------------------
def test_mxl009_flags_direct_profiler_use():
    mxlint = _import_tool("mxlint")
    src = ("import jax\n"
           "def capture():\n"
           "    jax.profiler.start_trace('/tmp/t')\n"
           "    with jax.profiler.TraceAnnotation('step'):\n"
           "        pass\n"
           "    jax.profiler.stop_trace()\n")
    registered, import_ok = mxlint.registered_env_names()
    found = [f["code"] for f in mxlint.ModuleLinter(
        os.path.join(ROOT, "mxnet_tpu", "rogue.py"), src,
        registered, import_ok, is_env_py=False).run()]
    assert found.count("MXL009") == 3, found
    # the sanctioned site itself is exempt
    clean = [f["code"] for f in mxlint.ModuleLinter(
        os.path.join(ROOT, "mxnet_tpu", "traceview", "x.py"), src,
        registered, import_ok, is_env_py=False).run()]
    assert "MXL009" not in clean, clean


def test_mxlint_repo_has_no_mxl009():
    mxlint = _import_tool("mxlint")
    registered, import_ok = mxlint.registered_env_names()
    findings = mxlint.lint_paths([os.path.join(ROOT, "mxnet_tpu")],
                                 registered, import_ok)
    assert not [f for f in findings if f["code"] == "MXL009"], \
        [f for f in findings if f["code"] == "MXL009"]


# ---------------------------------------------------------------------
# env-registry + docs drift for the capture knobs
# ---------------------------------------------------------------------
def test_trace_knobs_registered_and_documented():
    from mxnet_tpu import env

    reg = env.registered()
    with open(os.path.join(ROOT, "README.md")) as f:
        readme = f.read()
    for name in ("MXNET_TRACE_DIR", "MXNET_TRACE_STEPS"):
        assert name in reg, name
        assert reg[name].doc and len(reg[name].doc) > 10, name
        assert name in readme, "%s missing from README" % name
        assert name in env.describe()


# ---------------------------------------------------------------------
# OVERLAP_MEASURED.json v2: measurement labeled, simulation labeled
# ---------------------------------------------------------------------
def test_overlap_measured_v2_provenance_and_labels():
    with open(os.path.join(ROOT, "OVERLAP_MEASURED.json")) as f:
        blob = json.load(f)
    assert blob["format"] == "mxnet-tpu-overlap-measured"
    assert blob["version"] >= 2
    # the legacy r5 schedule-walk numbers survive for byte accounting
    # but are labeled as simulation, not measurement
    assert blob["source"] == "simulated"
    assert "schedule_walk" in blob
    note = json.dumps(blob["schedule_walk"]).lower()
    assert "walk" in note and "byte accounting" in note, note
    # the device_timeline block is a real capture with provenance
    dt = blob["device_timeline"]
    assert dt["source"] == "trace"
    assert dt["plan_match"] is True
    assert dt["buckets"] and all("occupancy" in b for b in dt["buckets"])
    assert dt["overlap_frac"] is not None
    prov = blob["provenance"]
    assert prov["platform"] and prov["workload"].startswith(
        "FusedTrainStep")
    assert "staleness" in blob and "device_timeline" in blob["staleness"]
    # test_overlap.py's legacy contract stays intact
    assert blob["overlap_measured"] is not None
    assert 30e6 < blob["n_sync_allreduce_bytes"] + blob["async_bytes"] \
        < 60e6
