"""Transformer-LM workload tier (ISSUE 13): pluggable attention as
TRAINABLE kernels on the sp=2 mesh, ZeRO-1 sharded optimizer state vs
the replicated control under the fp64/lr0 methodology, fused
multi-tensor optimizer numerics, exact checkpoint/resume through the
transformer fit path, the chaos kill/resume harness on the new
workload, and the generalized (model-agnostic) autotune leaf path."""
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import env as mxenv
from mxnet_tpu.parallel.attention import attention_reference
from mxnet_tpu.parallel.mesh import current_device_count, make_mesh
from mxnet_tpu.parallel.ring_attention import ring_attention
from mxnet_tpu.parallel.sequence import ulysses_attention
from mxnet_tpu.transformer import (LMTokenIter, TransformerConfig,
                                   TransformerTrainStep, attention_impl,
                                   init_params, make_corpus, param_shapes)

_WORKER = os.path.join(os.path.dirname(__file__), "transformer_worker.py")


def _need_devices(n):
    if current_device_count() < n:
        pytest.skip("needs %d virtual devices" % n)


def _cfg(**kw):
    base = dict(vocab_size=64, n_layers=2, d_model=32, n_heads=4,
                d_ff=64)
    base.update(kw)
    return TransformerConfig(**base)


def _iter(**kw):
    base = dict(batch_size=4, seq_len=16, vocab_size=64,
                num_sequences=32)
    base.update(kw)
    return LMTokenIter(**base)


# ---------------------------------------------------------------------------
# attention impls as TRAINABLE kernels (sp=2)
# ---------------------------------------------------------------------------
def _qkv(B=2, T=32, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda s: jnp.asarray(rng.randn(B, T, H, D), "float32")
    return mk(0), mk(1), mk(2)


def _sharded(fn, mesh, axis="sp"):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_attention_impls_trainable_sp2(impl):
    """forward AND grad of the sequence-parallel impls == full
    attention at ~1e-6 on the sp=2 mesh — trainable kernels, not just
    inference equivalence."""
    _need_devices(2)
    mesh = make_mesh((2,), ("sp",), jax.devices()[:2])
    q, k, v = _qkv()
    body = ring_attention if impl == "ring" else ulysses_attention
    fn = _sharded(
        lambda a, b, c: body(a, b, c, axis_name="sp", causal=True),
        mesh)

    def loss_sp(q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True)
                .astype(jnp.float32) ** 2).sum()

    np.testing.assert_allclose(np.asarray(fn(q, k, v)),
                               np.asarray(attention_reference(
                                   q, k, v, causal=True)),
                               atol=1e-6)
    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=1e-5)


def test_ring_causal_edge_blocks():
    """Causal-mask edge steps on the ring: the FULLY-MASKED rotation
    step (device 0 holding device 1's future KV block) must contribute
    NOTHING to the first shard's outputs, while the diagonal block
    stays causal within the shard."""
    _need_devices(2)
    mesh = make_mesh((2,), ("sp",), jax.devices()[:2])
    q, k, v = _qkv(T=16)
    fn = _sharded(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp",
                                       causal=True), mesh)
    out = np.asarray(fn(q, k, v))
    # perturb the SECOND shard's values: positions 0..7 attend only to
    # kv 0..7 (the second-half block is fully masked for them), so
    # their outputs are bit-identical; the second half must change
    v2 = v.at[:, 8:].add(100.0)
    out2 = np.asarray(fn(q, k, v2))
    np.testing.assert_array_equal(out2[:, :8], out[:, :8])
    assert np.abs(out2[:, 8:] - out[:, 8:]).max() > 1.0
    # diagonal block: within the second shard, position 8 sees only
    # kv<=8 — perturbing kv at position 9 leaves q-position 8 alone
    v3 = v.at[:, 9].add(100.0)
    out3 = np.asarray(fn(q, k, v3))
    np.testing.assert_array_equal(out3[:, :9], out[:, :9])


def test_ulysses_heads_not_divisible_raises():
    _need_devices(2)
    mesh = make_mesh((2,), ("sp",), jax.devices()[:2])
    q, k, v = _qkv(H=3)
    fn = _sharded(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp"),
        mesh)
    with pytest.raises(AssertionError, match="divide"):
        fn(q, k, v)
    # and the train step rejects it up front, before any compile
    _need_devices(4)
    mesh4 = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
    step = TransformerTrainStep(_cfg(n_heads=3, d_model=33),
                                mesh=mesh4, attn_impl="ulysses")
    with pytest.raises(ValueError, match="divide"):
        step._build()


def test_flash_rejected_on_sp_mesh():
    """flash over a sequence shard is WRONG math, not a slow path —
    the selector must refuse."""
    from mxnet_tpu.transformer import make_attn_fn

    with pytest.raises(ValueError, match="sequence-sharded"):
        make_attn_fn("flash", "sp")
    with pytest.raises(ValueError, match="sequence-parallel"):
        make_attn_fn("ring", None)


# ---------------------------------------------------------------------------
# training-tier numerics
# ---------------------------------------------------------------------------
def _fit_params(mesh=None, steps=4, **step_kw):
    it = _iter()
    cfg = step_kw.pop("cfg", _cfg())
    s = TransformerTrainStep(cfg, mesh=mesh, seed=0, **step_kw)
    losses = s.fit(it, steps)
    return losses, s.params_numpy(), s


def test_sequence_parallel_matches_single_chip():
    """ring and ulysses TRAINING trajectories on the dp=2 x sp=2 mesh
    match the single-device flash run at fp tolerance — the end-to-end
    proof the two orphaned kernels now carry a real workload."""
    _need_devices(4)
    l1, p1, _ = _fit_params(mesh=None, attn_impl="flash")
    mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
    for impl in ("ring", "ulysses"):
        ls, ps, s = _fit_params(mesh=mesh, attn_impl=impl)
        assert s.attention_impl == impl
        rel = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l1, ls))
        assert rel < 1e-5, "%s diverged from single-chip: %g" % (impl,
                                                                 rel)


def test_zero1_bitwise_lr0_and_fp64():
    """The fp64/lr0 control methodology applied to ZeRO-1: sharded
    optimizer state must match the replicated control BITWISE on the
    dp=2 mesh."""
    _need_devices(2)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    # lr=0: params never move; any drift is a sharding bug
    _, p_r, _ = _fit_params(mesh=mesh, zero_stage=0, learning_rate=0.0)
    _, p_z, sz = _fit_params(mesh=mesh, zero_stage=1, learning_rate=0.0)
    assert sz.zero1
    for k in p_r:
        np.testing.assert_array_equal(p_r[k], p_z[k])
    # fp64: reduction-order noise at ~1e-16 per op — psum vs
    # reduce-scatter must produce the same sums, so params stay bitwise
    cfg64 = _cfg(dtype="float64", param_dtype="float64")
    _, p_r, _ = _fit_params(mesh=mesh, cfg=cfg64, zero_stage=0)
    _, p_z, _ = _fit_params(mesh=mesh, cfg=cfg64, zero_stage=1)
    for k in p_r:
        np.testing.assert_array_equal(p_r[k], p_z[k])


def test_zero1_bf16_and_memory():
    """bf16 ZeRO-1 trajectory within ~1e-7 of the replicated control
    (bitwise on this 2-rank mesh, in fact), and the per-rank optimizer
    state measurably ~1/dp of replicated — from the LIVE buffers."""
    _need_devices(2)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    cfg16 = _cfg(dtype="bfloat16")
    l_r, p_r, s_r = _fit_params(mesh=mesh, cfg=cfg16, zero_stage=0)
    l_z, p_z, s_z = _fit_params(mesh=mesh, cfg=cfg16, zero_stage=1)
    rel = max(abs(a - b) / max(abs(a), 1e-9) for a, b in zip(l_r, l_z))
    assert rel <= 1e-7, "bf16 zero1 drifted: %g" % rel
    rep = s_r.optimizer_state_bytes_per_rank()
    shd = s_z.optimizer_state_bytes_per_rank()
    assert rep > 0 and shd > 0
    assert abs(shd / rep - 0.5) < 0.05, (shd, rep)


def test_fused_multi_tensor_matches_per_key_bitwise():
    """The fused one-op-over-all-params optimizer (optimizer.py
    fused_sgd_mom_flat through FusedTrainStep) is BITWISE identical to
    the per-key update loop — the ROADMAP item-5 numerics pin."""
    _need_devices(2)
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel.dp import FusedTrainStep

    def run(fused):
        np.random.seed(0)
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(16))
        net.initialize(mx.init.Xavier())
        mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
        step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh, learning_rate=0.05,
                              momentum=0.9, weight_decay=1e-4,
                              fused_update=fused)
        X = nd.random.uniform(shape=(8, 12))
        y = nd.array((np.arange(8) % 16).astype("float32"))
        losses = [float(step(X, y)[0].asnumpy()) for _ in range(3)]
        params = [p.data().asnumpy()
                  for _, p in sorted(net.collect_params().items())]
        return losses, params

    l_pk, p_pk = run(False)
    l_f, p_f = run(True)
    assert l_pk == l_f
    for a, b in zip(p_pk, p_f):
        np.testing.assert_array_equal(a, b)


def test_fused_train_step_zero1_matches_replicated():
    """MXNET_ZERO_STAGE threads through parallel/dp.py's conv-workload
    step too: zero1 == replicated bitwise on dp=2, with sharded
    momenta buffers."""
    _need_devices(2)
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel.dp import FusedTrainStep

    def run(stage):
        np.random.seed(0)
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(16))
        net.initialize(mx.init.Xavier())
        mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
        step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh, learning_rate=0.05,
                              momentum=0.9, zero_stage=stage,
                              bucket_bytes=1024)
        X = nd.random.uniform(shape=(8, 12))
        y = nd.array((np.arange(8) % 16).astype("float32"))
        losses = [float(step(X, y)[0].asnumpy()) for _ in range(3)]
        params = [p.data().asnumpy()
                  for _, p in sorted(net.collect_params().items())]
        return losses, params, step

    l0, p0, s0 = run(0)
    l1, p1, s1 = run(1)
    assert s1.zero1 and not s0.zero1
    assert l0 == l1
    for a, b in zip(p0, p1):
        np.testing.assert_array_equal(a, b)
    assert s1.optimizer_state_bytes_per_rank() < \
        s0.optimizer_state_bytes_per_rank()


def test_remat_policies_numerics():
    """block / attention remat recompute the SAME math — trajectories
    match the no-remat run to fp round-off (XLA fuses the recompute
    differently, so bitwise is not guaranteed; ~1e-7 is)."""
    l_none, p_none, _ = _fit_params(steps=2, remat="none")
    for pol in ("block", "attention"):
        l_p, p_p, _ = _fit_params(steps=2, remat=pol)
        rel = max(abs(a - b) / max(abs(a), 1e-9)
                  for a, b in zip(l_none, l_p))
        assert rel < 1e-6, (pol, rel)
        for k in p_none:
            np.testing.assert_allclose(p_none[k], p_p[k], atol=1e-6,
                                       rtol=1e-5)
    with pytest.raises(ValueError, match="remat policy"):
        _fit_params(steps=1, remat="everything")


# ---------------------------------------------------------------------------
# checkpoint / resume / chaos
# ---------------------------------------------------------------------------
def test_fit_resume_bitwise(tmp_path):
    """Exact resume through the transformer fit path: the ZeRO-1
    sharded momenta ride the elastic manifest and the resumed run is
    BITWISE the uninterrupted control."""
    _need_devices(2)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    ck = str(tmp_path / "ck")

    sc = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1)
    lc = sc.fit(_iter(), 6)

    sa = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1)
    sa.fit(_iter(), 3, checkpoint_every_n=3, checkpoint_dir=ck)
    # the shard carries sharded momenta through optimizer_states and
    # the manifest digests cover it
    from mxnet_tpu import checkpoint as ckpt

    payload = ckpt.load_checkpoint(ck)
    state = pickle.loads(payload["optimizer_states"])
    assert state["zero_stage"] == 1
    assert len(state["momenta"]) == state["n_buckets"]

    sb = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1)
    lb = sb.fit(_iter(), 6, resume_from=ck)
    assert lb == lc[3:]
    pc, pb = sc.params_numpy(), sb.params_numpy()
    for k in pc:
        np.testing.assert_array_equal(pc[k], pb[k])


def test_zero1_elastic_restage_across_dp(tmp_path):
    """The elastic restage acceptance (ROADMAP item 4's last gap): a
    stage-1 checkpoint written at one dp resumes at ANOTHER — 2→1 and
    1→2 — with the trajectory pinned against the uninterrupted dp=2
    control under the fp64 methodology, and per-rank momenta measured
    at ~1/dp' from the live buffers."""
    _need_devices(2)
    cfg = _cfg(dtype="float64", param_dtype="float64")
    mesh2 = make_mesh((2,), ("dp",), jax.devices()[:2])
    mesh1 = make_mesh((1,), ("dp",), jax.devices()[:1])

    sc = TransformerTrainStep(cfg, mesh=mesh2, seed=0, zero_stage=1)
    lc = sc.fit(_iter(), 6)
    pc = sc.params_numpy()

    # 2 → 1: the sharded flat momenta unpack into the replicated dict
    ck = str(tmp_path / "ck21")
    sa = TransformerTrainStep(cfg, mesh=mesh2, seed=0, zero_stage=1)
    sa.fit(_iter(), 3, checkpoint_every_n=3, checkpoint_dir=ck)
    state = pickle.loads(
        mx.checkpoint.load_checkpoint(ck)["optimizer_states"])
    assert state["zero_stage"] == 1 and state["dp"] == 2
    sb = TransformerTrainStep(cfg, mesh=mesh1, seed=0, zero_stage=1)
    lb = sb.fit(_iter(), 6, resume_from=ck)
    assert not sb.zero1  # dp=1: stage 1 degenerates to replicated
    for a, b in zip(lc[3:], lb):
        assert abs(a - b) < 1e-9, (lc[3:], lb)
    pb = sb.params_numpy()
    for k in pc:
        np.testing.assert_allclose(pc[k], pb[k], rtol=1e-10,
                                   atol=1e-12)

    # 1 → 2: the replicated dict packs back into sharded flats, and
    # the per-rank momenta really shrink to ~1/2
    ck = str(tmp_path / "ck12")
    s1 = TransformerTrainStep(cfg, mesh=mesh1, seed=0, zero_stage=1)
    s1.fit(_iter(), 3, checkpoint_every_n=3, checkpoint_dir=ck)
    s2 = TransformerTrainStep(cfg, mesh=mesh2, seed=0, zero_stage=1)
    l2 = s2.fit(_iter(), 6, resume_from=ck)
    assert s2.zero1
    for a, b in zip(lc[3:], l2):
        assert abs(a - b) < 1e-9, (lc[3:], l2)
    p2 = s2.params_numpy()
    for k in pc:
        np.testing.assert_allclose(pc[k], p2[k], rtol=1e-10,
                                   atol=1e-12)
    per_rank_sharded = s2.optimizer_state_bytes_per_rank()
    per_rank_repl = sb.optimizer_state_bytes_per_rank()
    assert abs(per_rank_sharded / per_rank_repl - 0.5) < 0.05, \
        (per_rank_sharded, per_rank_repl)


def test_resume_rejects_mismatched_bucket_plan(tmp_path):
    """Restage re-slices identical bucket layouts; a CAP change
    between runs still rejects loudly — it cannot re-bucket."""
    _need_devices(2)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    ck = str(tmp_path / "ck")
    s = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1,
                             bucket_bytes=1024)
    s.fit(_iter(), 2, checkpoint_every_n=2, checkpoint_dir=ck)
    s2 = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1,
                              bucket_bytes=1 << 22)
    with pytest.raises(ValueError, match="bucket"):
        s2.fit(_iter(), 4, resume_from=ck)


@pytest.mark.slow
def test_chaos_kill_resume_e2e(tmp_path):
    """The existing kill/resume harness covers the transformer tier:
    chaos kills the worker mid-fit (exit 137) after a checkpoint
    landed; a fresh process resumes and finishes BITWISE equal to the
    uninterrupted control."""
    _need_devices(2)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_DUMP_DIR"] = str(tmp_path)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (flags +
                        " --xla_force_host_platform_device_count=2"
                        ).strip()
    env.pop("MXNET_CHAOS", None)

    def run(mode, ckdir, out, chaos=None, check=True):
        e = dict(env)
        if chaos:
            e["MXNET_CHAOS"] = chaos
        proc = subprocess.run(
            [sys.executable, _WORKER, mode, ckdir, out],
            env=e, capture_output=True, text=True, timeout=600)
        if check:
            assert proc.returncode == 0, proc.stdout + proc.stderr
        return proc

    ctrl = str(tmp_path / "ctrl.npz")
    run("control", str(tmp_path / "ck_ctrl"), ctrl)

    ck = str(tmp_path / "ck")
    victim = run("victim", ck, str(tmp_path / "victim.npz"),
                 chaos="kill:step=5", check=False)
    assert victim.returncode == 137, victim.stdout + victim.stderr

    res = str(tmp_path / "resume.npz")
    run("resume", ck, res)
    a, b = np.load(ctrl), np.load(res)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


# ---------------------------------------------------------------------------
# iterator + knobs + generalized autotune path
# ---------------------------------------------------------------------------
def test_lm_token_iter_contract():
    it = _iter()
    b1 = it.next()
    assert b1.data[0].shape == (4, 16)
    assert str(b1.data[0].dtype) == "int32"
    # labels are the shifted tokens (tied next-token objective)
    d = b1.data[0].asnumpy()
    l = b1.label[0].asnumpy()
    corpus = make_corpus(32, 16, 64, seed=0)
    np.testing.assert_array_equal(d, corpus[:4, :-1])
    np.testing.assert_array_equal(l, corpus[:4, 1:])
    # deterministic across fresh iterators
    it2 = _iter()
    np.testing.assert_array_equal(d, it2.next().data[0].asnumpy())
    # host-only fetch for the decode pool
    it2.reset()
    data, label, pad = it2.next_raw()
    assert isinstance(data[0], np.ndarray) and pad == 0
    np.testing.assert_array_equal(data[0], d)


def test_lm_token_iter_parts_disjoint_exhaustive():
    full = _iter(num_parts=1).data[0][1]
    seen = []
    for part in range(2):
        seen.append(_iter(num_parts=2, part_index=part).data[0][1])
    got = np.concatenate(seen)
    assert got.shape[0] == full.shape[0]
    # strided slices: every row appears exactly once
    assert {r.tobytes() for r in got} == {r.tobytes() for r in full}


def test_lm_token_iter_skip_batches_replay():
    it = _iter()
    it.next(), it.next()
    b3 = it.next().data[0].asnumpy()
    it2 = _iter()
    it2.reset()
    it2.skip_batches(2)
    np.testing.assert_array_equal(b3, it2.next().data[0].asnumpy())


def test_env_knobs(monkeypatch):
    for name in ("MXNET_ATTENTION_IMPL", "MXNET_REMAT_POLICY",
                 "MXNET_ZERO_STAGE", "MXNET_BENCH_TRANSFORMER"):
        assert mxenv.is_registered(name), name
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "ulysses")
    assert attention_impl() == "ulysses"
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "flesh")
    with pytest.raises(ValueError, match="attention impl"):
        attention_impl()
    from mxnet_tpu.parallel.dp import zero1_stage

    monkeypatch.setenv("MXNET_ZERO_STAGE", "3")
    with pytest.raises(ValueError, match="ZERO_STAGE"):
        zero1_stage()
    monkeypatch.setenv("MXNET_ZERO_STAGE", "1")
    assert zero1_stage() == 1
    from mxnet_tpu.remat import remat_policy

    monkeypatch.setenv("MXNET_REMAT_POLICY", "attention")
    assert remat_policy() == "attention"


def test_grad_entries_generalized():
    """scaling.grad_entries consumes any name->leaf mapping or an
    entry list, skips frozen params, and feeds the autotuner for the
    attention-dominated pattern (the resnet50_* names stay as
    wrappers over it)."""
    from mxnet_tpu.parallel import scaling

    # plain arrays
    params = {"a": np.zeros((4, 8), np.float32),
              "b": np.zeros((16,), np.float32)}
    ents = scaling.grad_entries(params)
    assert ents == [("a", (4, 8), "float32"), ("b", (16,), "float32")]
    assert scaling.grad_leaf_bytes(ents) == [128, 64]

    class P:
        def __init__(self, shape, grad_req="write"):
            self.shape, self.dtype = shape, "float32"
            self.grad_req = grad_req

    ents = scaling.grad_entries({"w": P((2, 2)),
                                 "frozen": P((9,), "null")})
    assert [e[0] for e in ents] == ["w"]
    # dtype override (the bf16-wire projection)
    ents = scaling.grad_entries(param_shapes(_cfg()), dtype="bfloat16")
    assert all(e[2] == "bfloat16" for e in ents)
    assert ents[0][0] == "embed"

    # the full tune path over the transformer leaves, no jax needed
    from mxnet_tpu import autotune

    leaf = scaling.grad_leaf_bytes(ents)
    tm = autotune.from_leaf_bytes(leaf, dtype="bfloat16",
                                  step_time_s=0.05,
                                  source={"kind": "transformer-test"})
    tuned = autotune.tune(tm, chips=256)
    assert 0 < tuned["score"]["eff"] <= 1.0
    assert "default_eff" in tuned["score"]


def test_autotune_plan_applies_to_transformer(tmp_path, monkeypatch):
    """A persisted tuned plan (MXNET_AUTOTUNE_PLAN) drives the
    transformer step's bucket caps — the closed loop now covers the
    attention comm pattern."""
    _need_devices(2)
    from mxnet_tpu import autotune
    from mxnet_tpu.autotune import plan as aplan
    from mxnet_tpu.parallel import scaling

    cfg = _cfg()
    ents = scaling.grad_entries(param_shapes(cfg))
    leaf = scaling.grad_leaf_bytes(ents)
    tm = autotune.from_leaf_bytes(leaf, dtype="float32",
                                  step_time_s=0.05,
                                  source={"kind": "transformer-test"})
    tuned = autotune.tune(tm, chips=256)
    path = str(tmp_path / "plan.json")
    aplan.save_plan(tuned, path)
    monkeypatch.setenv("MXNET_AUTOTUNE_PLAN", path)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    step = TransformerTrainStep(cfg, mesh=mesh, seed=0)
    step._build()
    tuning = step.bucket_tuning()
    assert tuning is not None and tuning["plan_path"] == path
    meta = step.bucket_plan_meta()
    assert meta["workload"] == "transformer_lm"
    assert meta.get("autotune", {}).get("plan_path") == path


def test_bucket_plan_rides_flight_header():
    """The transformer step stamps its plan into the flight-recorder
    header like every other workload."""
    _need_devices(2)
    from mxnet_tpu import diagnostics as diag

    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    s = TransformerTrainStep(_cfg(), mesh=mesh, seed=0, zero_stage=1)
    it = _iter()
    b = it.next()
    np.asarray(s.step(b.data[0], b.label[0]))
    plan = diag.bucket_plan()
    assert plan is not None
    assert plan.get("workload") == "transformer_lm"
    assert plan.get("zero_stage") == 1


def test_param_shapes_match_init():
    cfg = _cfg()
    shapes = param_shapes(cfg)
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert [n for n, _, _ in shapes] == list(params)
    for name, shape, dtype in shapes:
        assert tuple(params[name].shape) == shape
        assert str(params[name].dtype) == dtype


def test_loss_learns_bigram_structure():
    """The synthetic stream is learnable: loss drops below the uniform
    floor log(V) within a handful of steps."""
    import math

    s = TransformerTrainStep(_cfg(), seed=0, learning_rate=0.05)
    losses = s.fit(_iter(num_sequences=64, batch_size=8), 12)
    assert losses[-1] < math.log(64) - 0.2, losses
