"""Generation-engine e2e on the REAL transformer (XLA-compiled plan
cells): the paged scatter/gather round trip is bitwise invisible to
attention, greedy continuous-batched paged decode matches the
dense-cache whole-prompt reference token for token (including a
cache-bucket promotion mid-generation), finished slots refill without
draining co-riders, every plan cell stays at its single warmup compile
under mixed traffic (and the decode auditor agrees), a chaos cancel
storm leaks zero blocks, and token streaming works end-to-end over
chunked HTTP.

The ``zz`` prefix is deliberate: this module sorts after
test_transformer.py so its XLA compile cost lands at the tail of a
time-boxed tier-1 run — the cheap no-compile generation units live in
tests/test_generate.py."""
import json
import socket
import struct
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import chaos
from mxnet_tpu import diagnostics as diag
from mxnet_tpu import serving
from mxnet_tpu.serving import reqtrace
from mxnet_tpu.transformer import model as tm


# ---------------------------------------------------------------------
# paged scatter -> block-table gather == the dense cache, BITWISE
# ---------------------------------------------------------------------
def test_scatter_gather_matches_dense_attention_bitwise():
    import jax.numpy as jnp

    bt, H, Dh = 16, 2, 8
    # ragged lengths straddling block/bucket boundaries
    lens = [3, 16, 17, 33]
    B, W = len(lens), 3                  # 3 blocks cover max len 33
    T = W * bt
    rng = np.random.RandomState(0)
    dense_k = rng.randn(B, T, H, Dh).astype(np.float32)
    dense_v = rng.randn(B, T, H, Dh).astype(np.float32)
    tables = np.zeros((B, W), dtype=np.int32)
    nxt = 1                              # block 0 is the garbage block
    for i, ln in enumerate(lens):
        nb = -(-ln // bt)
        tables[i, :nb] = np.arange(nxt, nxt + nb)
        nxt += nb
    pool_shape = (nxt, bt, H, Dh)
    pos = np.broadcast_to(np.arange(T), (B, T))
    valid = pos < np.asarray(lens)[:, None]
    k_pool = tm._scatter_tokens(jnp.zeros(pool_shape, jnp.float32),
                                jnp.asarray(dense_k),
                                jnp.asarray(tables), jnp.asarray(pos),
                                bt, valid=jnp.asarray(valid))
    v_pool = tm._scatter_tokens(jnp.zeros(pool_shape, jnp.float32),
                                jnp.asarray(dense_v),
                                jnp.asarray(tables), jnp.asarray(pos),
                                bt, valid=jnp.asarray(valid))
    gk, gv = tm.gather_kv({"k0": k_pool, "v0": v_pool},
                          jnp.asarray(tables), 0)
    gk, gv = np.asarray(gk), np.asarray(gv)
    # the gathered valid region is the dense cache, bit for bit
    for i, ln in enumerate(lens):
        assert np.array_equal(gk[i, :ln], dense_k[i, :ln])
        assert np.array_equal(gv[i, :ln], dense_v[i, :ln])
    # and attention under the length mask cannot tell them apart:
    # identical inputs on the valid rows + masked scores on the rest
    q = jnp.asarray(rng.randn(B, 1, H, Dh).astype(np.float32))
    mask = jnp.asarray(pos[:, None, :] < np.asarray(lens)[:, None,
                                                          None])
    out_paged = tm._masked_attn(q, jnp.asarray(gk), jnp.asarray(gv),
                                mask)
    out_dense = tm._masked_attn(q, jnp.asarray(dense_k),
                                jnp.asarray(dense_v), mask)
    assert np.array_equal(np.asarray(out_paged), np.asarray(out_dense))


# ---------------------------------------------------------------------
# the engine: greedy equality, continuous refill, recompile discipline
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def grt():
    rt = serving.demo_generation_runtime(
        "gen_t", n_layers=1, slots=2, block_tokens=16, max_prompt=16,
        max_context=64, max_new=32, prefill_batch=2)
    rt.compile(warmup=True)
    return rt


def _dense_greedy(rt, prompt, n_new):
    import jax.numpy as jnp

    toks = [int(t) for t in prompt]
    out = []
    for _ in range(n_new):
        arr = np.asarray(toks, dtype=np.int32)  # mxlint: disable=MXL004
        logits = tm.apply(rt._params, jnp.asarray(arr[None]), rt.cfg,
                          attn_fn=tm.dense_causal_attn)
        last = np.asarray(logits)  # mxlint: disable=MXL004
        nxt = int(last[0, -1].argmax())
        out.append(nxt)
        toks.append(nxt)
    return out


def test_greedy_matches_dense_reference_across_promotion(grt):
    # prompt 12 + 24 new tokens ends at 36: the sequence crosses the
    # 16- and 32-token cache buckets mid-generation (two promotions);
    # 3 requests on 2 slots also forces a waiting-line admission
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, grt.cfg.vocab_size, size=n).tolist()
               for n in (3, 12, 16)]
    reqs = [serving.GenRequest("gen_t", p, 24) for p in prompts]
    for r in reqs:
        grt.engine.enqueue(r)
    while not grt.engine.idle():
        grt.engine.step()
    for p, r in zip(prompts, reqs):
        got = r.wait(0.1)["tokens"]
        assert got == _dense_greedy(grt, p, 24), \
            "paged/continuous greedy diverged for prompt len %d" % len(p)
    assert grt.kv.stats()["blocks_live"] == 0


def test_continuous_batching_refills_slots(grt):
    # 5 sequences on 2 slots, 8 tokens each: serial would cost 40
    # decode ticks — continuous refill lands well under that
    t0 = grt.engine.ticks
    reqs = [serving.GenRequest("gen_t", [i + 1, i + 2], 8)
            for i in range(5)]
    for r in reqs:
        grt.engine.enqueue(r)
    while not grt.engine.idle():
        grt.engine.step()
    assert all(len(r.wait(0.1)["tokens"]) == 8 for r in reqs)
    assert grt.engine.ticks - t0 < 32
    assert grt.kv.stats()["blocks_live"] == 0


def test_zero_steady_state_recompiles_and_audit_clean(grt):
    # drive fresh mixed-shape traffic, then prove every plan cell is
    # still at its single warmup compile and the auditor agrees
    for p, n in (([1, 2, 3], 6), (list(range(1, 14)), 20)):
        r = serving.GenRequest("gen_t", p, n)
        grt.engine.enqueue(r)
        while not grt.engine.idle():
            grt.engine.step()
        r.wait(0.1)
    counts = {k: v["count"] for k, v in diag.recompile_stats().items()
              if ":gen_t:" in k}
    assert len(counts) == len(grt.prefill_plan) + len(grt.decode_plan)
    assert set(counts.values()) == {1}, counts
    from mxnet_tpu import analysis

    rep = analysis.audit_decode_buckets()
    site = "generate_decode:gen_t"
    assert site in rep.sites
    assert not [f for f in rep.findings
                if f.site == site], rep.summary()
    assert rep.sites[site]["compiles"] == len(grt.decode_plan)


def test_cancel_storm_zero_leaked_blocks(grt, monkeypatch):
    # chaos cancel_request: 4 mid-stream disconnects across the run;
    # cancelled sequences reclaim slot+blocks next tick, co-riders
    # finish their full 16 tokens
    monkeypatch.setenv("MXNET_CHAOS",
                       "cancel_request:model=gen_t,nth=3,count=4")
    chaos.reset()
    reqtrace.reset(capacity=32, topk=4)
    try:
        reqs = [serving.GenRequest("gen_t", [i + 1, i + 7, i + 3], 16)
                for i in range(6)]
        for r in reqs:
            grt.engine.enqueue(r)
        while not grt.engine.idle():
            grt.engine.step()
    finally:
        monkeypatch.delenv("MXNET_CHAOS")
        chaos.reset()
        snap = reqtrace.snapshot()
        reqtrace.reset()
    cancelled = ok = 0
    for r in reqs:
        try:
            res = r.wait(0.1)
            assert len(res["tokens"]) == 16  # co-riders untouched
            ok += 1
        except serving.Cancelled:
            cancelled += 1
    assert cancelled == 4 and ok == 2
    assert grt.kv.stats()["blocks_live"] == 0
    assert grt.kv.stats()["blocks_free"] == grt.kv.num_blocks - 1
    # ...and the storm leaves the request-trace ring CONSISTENT: every
    # record reached a terminal span (no orphan open records), with the
    # same 4-cancelled/2-ok split the futures report
    assert not snap["open"], [r["id"] for r in snap["open"]]
    outcomes = [r["outcome"] for r in snap["recent"]]
    assert outcomes.count("cancelled") == 4
    assert outcomes.count("ok") == 2


def test_reqtrace_deadline_expiry_dies_waiting(grt, monkeypatch,
                                               tmp_path):
    # two blockers occupy both slots; the doomed request's 5 ms
    # deadline expires in the waiting line.  Its terminal reqtrace
    # span must say expired-while-WAITING (queue residency only, no
    # execute phase), and the blown deadline must auto-dump the
    # autopsy file
    monkeypatch.setenv("MXNET_DUMP_DIR", str(tmp_path))
    reqtrace.reset(capacity=32, topk=4)
    try:
        blockers = [serving.GenRequest("gen_t", [1, 2, 3], 12)
                    for _ in range(2)]
        for r in blockers:
            grt.engine.enqueue(r)
        grt.engine.step()  # both slots now occupied
        doomed = serving.GenRequest("gen_t", [4, 5], 12,
                                    deadline_s=0.001)
        grt.engine.enqueue(doomed)
        time.sleep(0.01)  # the deadline lapses in the waiting line
        while not grt.engine.idle():
            grt.engine.step()
        with pytest.raises(serving.DeadlineExceeded):
            doomed.wait(0.1)
        for r in blockers:
            assert len(r.wait(0.1)["tokens"]) == 12
        snap = reqtrace.snapshot()
        assert not snap["open"]
        rec = next(r for r in snap["recent"] if r["id"] == doomed.id)
        assert rec["outcome"] == "expired"
        assert "queue" in rec["phases"]
        assert not any(k in rec["phases"]
                       for k in ("prefill", "decode", "execute"))
        assert reqtrace.recorder.model_summary()["gen_t"][
            "died_waiting"] >= 1
        dumps = sorted(tmp_path.glob("reqtrace_rank*.json"))
        assert dumps, "a blown deadline must auto-dump the autopsy"
        payload = json.loads(dumps[0].read_text())
        assert payload["header"]["reason"] == "deadline"
        assert payload["header"]["format"] == reqtrace.REQTRACE_FORMAT
    finally:
        reqtrace.reset()
    assert grt.kv.stats()["blocks_live"] == 0


# ---------------------------------------------------------------------
# streaming HTTP e2e: chunked :generate, per-token lines, cancel=499
# ---------------------------------------------------------------------
def test_http_generate_streaming_e2e(monkeypatch):
    rt = serving.demo_generation_runtime(
        "gen_http", n_layers=1, slots=1, block_tokens=16,
        max_prompt=16, max_context=32, max_new=8, prefill_batch=1)
    srv = serving.ModelServer(queue_max=8, default_deadline_ms=30000)
    srv.add_generator(rt)
    fe = serving.HttpFrontend(srv, port=0)
    host, port = fe.start()
    base = "http://%s:%d" % (host, port)
    try:
        # blocking path first: the reference token list
        req = urllib.request.Request(
            base + "/v1/models/gen_http:generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "max_new": 6}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        blocking = json.loads(resp.read())
        assert resp.status == 200 and len(blocking["tokens"]) == 6
        # streaming path: urllib transparently de-chunks; the body is
        # one JSON line per token + the done record
        req = urllib.request.Request(
            base + "/v1/models/gen_http:generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_new": 6,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        assert resp.headers.get("Transfer-Encoding") == "chunked"
        lines = [json.loads(ln) for ln in
                 resp.read().decode().splitlines() if ln]
        assert lines[-1] == {"done": True, "tokens": 6,
                             "prompt_len": 3}
        assert [ln["token"] for ln in lines[:-1]] == blocking["tokens"]
        assert [ln["index"] for ln in lines[:-1]] == list(range(6))
        # oversized prompt sheds at submit with too_large
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/models/gen_http:generate",
                data=json.dumps({"prompt": list(range(99))}).encode()))
        assert ei.value.code == 413
        assert json.loads(ei.value.read())["reason"] == "too_large"
        # streaming client-disconnect (the 499 convention): kill the
        # socket mid-stream under an injected decode stall (so the
        # engine is still generating when the RST lands); the terminal
        # reqtrace span must say cancelled — never ok — with the
        # disconnect event recorded and the stall spans tagged injected
        monkeypatch.setenv(
            "MXNET_CHAOS",
            "stall_decode_tick:model=gen_http,ms=30,count=999")
        chaos.reset()
        reqtrace.reset(capacity=32, topk=4)
        try:
            body = json.dumps({"prompt": [1, 2, 3], "max_new": 8,
                               "stream": True})
            sk = socket.create_connection((host, port), timeout=10)
            sk.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                          struct.pack("ii", 1, 0))  # close sends RST
            sk.sendall(("POST /v1/models/gen_http:generate HTTP/1.1\r\n"
                        "Host: t\r\nContent-Type: application/json\r\n"
                        "Content-Length: %d\r\n\r\n%s"
                        % (len(body), body)).encode())
            assert sk.recv(1)  # response started: the stream is live
            sk.close()
            rec, deadline = None, time.monotonic() + 20.0
            while time.monotonic() < deadline:
                snap = reqtrace.snapshot()
                done = [r for r in snap["recent"]
                        if r["model"] == "gen_http"]
                if done:
                    rec = done[0]
                    break
                time.sleep(0.05)
            assert rec is not None, "disconnected request never closed"
            assert rec["outcome"] == "cancelled"
            assert "client_disconnect" in rec["events"]
            assert rec["injected_any"]  # chaos stall never reads organic
            assert not snap["open"]
        finally:
            monkeypatch.delenv("MXNET_CHAOS")
            chaos.reset()
            reqtrace.reset()
    finally:
        fe.stop()
        srv.drain(timeout_s=10.0)
