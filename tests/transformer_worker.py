"""Transformer-tier chaos worker: the kill/resume harness applied to
the new workload (test_transformer.py runs it three ways):

  control — uninterrupted 8-step ZeRO-1 fit on the dp=2 CPU mesh;
            dumps final params.
  victim  — MXNET_CHAOS kills the process mid-fit (exit 137) after the
            step-4 checkpoint landed.
  resume  — fresh process resumes from the newest complete step and
            finishes; final params must match control BITWISE (same
            world, same bucket plan, deterministic iterator).

Usage: transformer_worker.py <mode> <ckpt_dir> <out_path>
"""
import sys

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel.mesh import make_mesh  # noqa: E402
from mxnet_tpu.transformer import (LMTokenIter,  # noqa: E402
                                   TransformerConfig,
                                   TransformerTrainStep)


def main():
    mode, ckpt_dir, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
    cfg = TransformerConfig(vocab_size=64, n_layers=2, d_model=32,
                            n_heads=4, d_ff=64)
    mesh = make_mesh((2,), ("dp",), jax.devices()[:2])
    step = TransformerTrainStep(cfg, mesh=mesh, seed=0, zero_stage=1)
    it = LMTokenIter(batch_size=4, seq_len=16, vocab_size=64,
                     num_sequences=32)
    kw = dict(checkpoint_every_n=2, checkpoint_dir=ckpt_dir)
    if mode == "resume":
        kw["resume_from"] = ckpt_dir
    step.fit(it, 8, **kw)
    np.savez(out_path, **step.params_numpy())
    print("transformer worker done (%s)" % mode)


if __name__ == "__main__":
    main()
