"""Drive the reference warpctc example byte-identical (VERDICT r4
missing #3 / next-round #6): plugin/warpctc's worked example trained
through the WarpCTC creator.

All modeling and decode code is the reference's own, imported straight
from /root/reference/example/warpctc: ``lstm.lstm_unroll`` (which ends
in ``mx.sym.WarpCTC`` — lstm.py:94), ``toy_ctc.DataIter`` (the
no-external-deps synthetic digit task), ``toy_ctc.Accuracy`` (exact
sequence match after the script's own CTC best-path decode).  Only the
driver knobs shrink (batch/hidden/epochs) — toy_ctc's __main__ trains
100k batches on GPU, which is its scale choice, not its semantics.
"""
import os
import random
import sys

import numpy as np

REFERENCE = "/root/reference"
sys.path.insert(0, os.path.join(REFERENCE, "example", "warpctc"))

import mxnet as mx  # noqa: E402  (compat shim via PYTHONPATH)

import toy_ctc  # noqa: E402  (reference module, byte-identical)
from lstm import lstm_unroll  # noqa: E402

random.seed(7)
np.random.seed(7)
mx.random.seed(7)

BATCH = 16
toy_ctc.BATCH_SIZE = BATCH  # module global consumed by its Accuracy
NUM_HIDDEN = 32
NUM_LABEL = 4
# CTC must escape the emit-only-blank local optimum before sequence
# accuracy moves at all: a pure-JAX twin of this exact task (LSTM-32,
# T=80, 20 frames/digit) plateaus at loss~3.4 with acc 0 until ~1200
# updates, then snaps to acc 1.0 by ~1500 (lr 0.01, momentum 0.9).
# 90 batches x 20 epochs = 1800 updates clears that knee with margin;
# the reference's own scale choice was 100k batches/epoch on GPU.
NUM_EPOCH = 36
BATCHES_PER_EPOCH = 90

# K train steps per XLA dispatch — the bulk fit path (our framework's
# knob, engine.set_bulk_size; toy_ctc itself is untouched)
from mxnet_tpu import engine  # noqa: E402

engine.set_bulk_size(10)

init_states = [("l0_init_c", (BATCH, NUM_HIDDEN)),
               ("l0_init_h", (BATCH, NUM_HIDDEN))]
data_train = toy_ctc.DataIter(BATCHES_PER_EPOCH, BATCH, NUM_LABEL,
                              init_states)
data_val = toy_ctc.DataIter(8, BATCH, NUM_LABEL, init_states)

symbol = lstm_unroll(1, toy_ctc.SEQ_LENGTH, num_hidden=NUM_HIDDEN,
                     num_label=NUM_LABEL)

# init: the example's Xavier(magnitude=2.34) saturates this LSTM at
# CI scale — a pure-JAX twin of the exact task shows loss pinned at
# ~3.4 (the all-blank optimum) for 4000+ updates under that init,
# while Normal(0.08) breaks through at ~1500.  The init is the
# driver's knob (FeedForward argument), not reference code.
# lr decays past the breakout knee: the unclipped run escapes the
# blank optimum around epoch 10-16 but a full-rate momentum step at
# the alignment transition throws it back (observed 2x weight jump);
# halving lr every 12 epochs keeps the post-knee steps small
model = mx.model.FeedForward(
    ctx=[mx.cpu()], symbol=symbol, num_epoch=NUM_EPOCH,
    learning_rate=0.012, momentum=0.9, wd=0.00001,
    lr_scheduler=mx.lr_scheduler.FactorScheduler(
        step=12 * BATCHES_PER_EPOCH, factor=0.33),
    initializer=mx.init.Normal(0.08))

val_accs = []


def _eval_cb(params):
    for name, value in params.eval_metric.get_name_value():
        val_accs.append(value)
    print("WARPCTC_EPOCH_ACC %d %.4f" % (len(val_accs), val_accs[-1]),
          flush=True)


model.fit(X=data_train, eval_data=data_val,
          eval_metric=mx.metric.np(toy_ctc.Accuracy),
          eval_end_callback=_eval_cb)

print("WARPCTC_VAL_ACCS", " ".join("%.4f" % a for a in val_accs))
# exact-4-digit-sequence match: chance is 1e-4 (wrong-length or any
# wrong digit fails the whole sequence).  Measured trajectory at this
# budget: 0 until the ~epoch-29 breakout, then 0.10-0.16 sustained —
# three orders of magnitude above chance, with every digit flowing
# through WarpCTC's forward softmax and CTC gradient.
assert len(val_accs) == NUM_EPOCH, val_accs
assert max(val_accs[-6:]) > 0.1, val_accs
print("WARPCTC_OK final=%.4f" % val_accs[-1])
