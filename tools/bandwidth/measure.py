#!/usr/bin/env python
"""KVStore bandwidth benchmark (ref: tools/bandwidth/measure.py —
measures push+pull throughput over a kvstore backend with model-sized
gradient arrays).

    python tools/bandwidth/measure.py --kv-store local --num-layers 10
    python tools/launch.py -n 2 python tools/bandwidth/measure.py \
        --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--num-layers", type=int, default=10)
    ap.add_argument("--size", type=int, default=1 << 20,
                    help="floats per layer (default 1M ≈ 4MB)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--optimizer", default=None,
                    help="e.g. sgd — enables update_on_kvstore path")
    args = ap.parse_args()

    kv = mx.kv.create(args.kv_store)
    shapes = [(args.size,)] * args.num_layers
    grads = [nd.ones(s) for s in shapes]
    outs = [nd.zeros(s) for s in shapes]
    keys = list(range(args.num_layers))
    for k, g in zip(keys, grads):
        kv.init(k, nd.zeros(g.shape))
    if args.optimizer:
        kv.set_optimizer(mx.optimizer.create(args.optimizer))

    def one_round():
        kv.push(keys, grads)
        kv.pull(keys, out=outs)
        for o in outs:
            o.wait_to_read()

    for _ in range(args.warmup):
        one_round()
    tic = time.time()
    for _ in range(args.iters):
        one_round()
    dt = time.time() - tic
    nbytes = args.num_layers * args.size * 4
    # push + pull both move the full model per round
    gbps = 2 * nbytes * args.iters / dt / 1e9
    print("kvstore=%s rank=%d/%d: %.3f GB/s (%.1f ms/round, %d x %.1f MB)"
          % (args.kv_store, kv.rank, kv.num_workers, gbps,
             1e3 * dt / args.iters, args.num_layers, nbytes /
             args.num_layers / 1e6))
    if hasattr(kv, "close"):
        kv.close()


if __name__ == "__main__":
    main()
