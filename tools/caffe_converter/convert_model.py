"""Caffe -> mxnet_tpu model converter (LeNet/CaffeNet layer families).

The reference's tools/caffe_converter/{convert_symbol,convert_model}.py
walk a protoc-compiled NetParameter and emit mx.symbol calls + param
NDArrays; this build does the same over proto_lite/prototxt (no protoc,
no caffe install).  Supported layer types — the classic model-zoo set:
Input/Data, Convolution, Pooling (MAX/AVE, global), InnerProduct, ReLU,
Dropout, LRN, Softmax/SoftmaxWithLoss, Flatten, Concat, Eltwise(SUM).

Weight layouts match directly: caffe conv blobs are (out, in, kh, kw)
and InnerProduct blobs (out, in) — the same layouts Convolution /
FullyConnected consume here, so blobs copy over without transposition
(ref convert_model.py does the identical passthrough).

CLI (ref run.sh):  python convert_model.py net.prototxt net.caffemodel
                   out_prefix   -> out_prefix-symbol.json + -0000.params
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from tools.caffe_converter.proto_lite import parse_caffemodel
from tools.caffe_converter.prototxt import parse_prototxt

__all__ = ["convert", "convert_symbol"]


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _kernel_pair(param, base, default=0):
    """caffe allows kernel_size or kernel_h/kernel_w (same for stride,
    pad); repeated keys (legal protobuf text for per-dim values) parse
    to lists — h then w."""

    def _pair(v):
        if isinstance(v, list):
            return (int(v[0]), int(v[1]) if len(v) > 1 else int(v[0]))
        return (int(v), int(v))

    if base + "_size" in param:
        return _pair(param[base + "_size"])
    if base in param:  # stride / pad spelled bare
        return _pair(param[base])
    h = int(param.get(base + "_h", default))
    w = int(param.get(base + "_w", default))
    return (h, w)


def convert_symbol(prototxt_text):
    """-> (Symbol, input_name).  Mirrors the reference convert_symbol.py
    layer walk."""
    import mxnet_tpu as mx

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer"))
    if not layers:
        raise ValueError("prototxt has no V2 'layer' entries")

    tops = {}
    input_name = None

    # standalone inputs: `input: "data"` or Input layers
    for inp in _as_list(net.get("input")):
        tops[inp] = mx.sym.Variable(inp)
        input_name = input_name or inp

    for layer in layers:
        ltype = layer.get("type")
        name = layer.get("name")
        bottoms = _as_list(layer.get("bottom"))
        ins = [tops[b] for b in bottoms if b in tops]
        top = _as_list(layer.get("top"))
        out = None
        if ltype in ("Input", "Data"):
            out = mx.sym.Variable(top[0] if top else name)
            input_name = input_name or (top[0] if top else name)
        elif ltype == "Convolution":
            p = layer.get("convolution_param", {})
            out = mx.sym.Convolution(
                ins[0], name=name,
                num_filter=int(p["num_output"]),
                kernel=_kernel_pair(p, "kernel"),
                stride=_kernel_pair(p, "stride", 1),
                pad=_kernel_pair(p, "pad", 0),
                num_group=int(p.get("group", 1)),
                no_bias=not bool(p.get("bias_term", True)))
        elif ltype == "Pooling":
            p = layer.get("pooling_param", {})
            pool = str(p.get("pool", "MAX")).upper()
            ptype = {"MAX": "max", "AVE": "avg"}[pool]
            if p.get("global_pooling"):
                out = mx.sym.Pooling(ins[0], name=name, global_pool=True,
                                     kernel=(1, 1), pool_type=ptype)
            else:
                out = mx.sym.Pooling(
                    ins[0], name=name, pool_type=ptype,
                    kernel=_kernel_pair(p, "kernel"),
                    stride=_kernel_pair(p, "stride", 1),
                    pad=_kernel_pair(p, "pad", 0),
                    # caffe pooling rounds UP (ceil) — the reference
                    # converter emits pooling_convention='full'
                    pooling_convention="full")
        elif ltype == "InnerProduct":
            p = layer.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                mx.sym.Flatten(ins[0]), name=name,
                num_hidden=int(p["num_output"]),
                no_bias=not bool(p.get("bias_term", True)))
        elif ltype == "ReLU":
            out = mx.sym.Activation(ins[0], name=name, act_type="relu")
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            out = mx.sym.Dropout(ins[0], name=name,
                                 p=float(p.get("dropout_ratio", 0.5)))
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = mx.sym.LRN(ins[0], name=name,
                             nsize=int(p.get("local_size", 5)),
                             alpha=float(p.get("alpha", 1e-4)),
                             beta=float(p.get("beta", 0.75)))
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            out = mx.sym.SoftmaxOutput(ins[0], name=name)
        elif ltype == "Flatten":
            out = mx.sym.Flatten(ins[0], name=name)
        elif ltype == "Concat":
            out = mx.sym.Concat(*ins, name=name)
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op = str(p.get("operation", "SUM")).upper()
            if op != "SUM":
                raise ValueError("Eltwise %s not supported" % op)
            out = ins[0]
            for extra in ins[1:]:
                out = out + extra
        elif ltype in ("Accuracy",):
            continue  # eval-only layers drop out of the deploy graph
        else:
            raise ValueError("unsupported caffe layer type %r (%s)"
                             % (ltype, name))
        for t in (top or [name]):
            tops[t] = out
        tops[name] = out

    # the network output is the last CONVERTED layer's top: a train-
    # style prototxt may end in eval-only layers (Accuracy) that were
    # skipped above and never populated `tops`
    sym = None
    for layer in reversed(layers):
        key = (_as_list(layer.get("top")) or [layer.get("name")])[0]
        if key in tops:
            sym = tops[key]
            break
    if sym is None:
        raise ValueError("prototxt has no convertible output layer "
                         "(only eval-only layers found)")
    return sym, input_name or "data"


def convert(prototxt_path, caffemodel_path):
    """-> (Symbol, arg_params, aux_params) — the reference
    convert_model.py contract."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    with open(prototxt_path) as f:
        sym, input_name = convert_symbol(f.read())
    with open(caffemodel_path, "rb") as f:
        model = parse_caffemodel(f.read())

    arg_params = {}
    for layer in model["layers"]:
        blobs = layer["blobs"]
        if not blobs:
            continue
        name = layer["name"]
        w = np.asarray(blobs[0]["data"], np.float32).reshape(
            blobs[0]["shape"])
        arg_params[name + "_weight"] = nd.array(w)
        if len(blobs) > 1:
            b = np.asarray(blobs[1]["data"], np.float32).reshape(-1)
            arg_params[name + "_bias"] = nd.array(b)
    return sym, arg_params, {}


def main():
    import mxnet_tpu as mx

    prototxt, caffemodel, prefix = sys.argv[1:4]
    sym, arg_params, aux_params = convert(prototxt, caffemodel)
    mx.model.save_checkpoint(prefix, 0, sym, arg_params, aux_params)
    print("saved %s-symbol.json / %s-0000.params" % (prefix, prefix))


if __name__ == "__main__":
    main()
