"""Minimal protobuf wire-format reader/writer for Caffe model blobs.

The reference converter (tools/caffe_converter/caffe_parser.py) compiles
the full caffe.proto with protoc and loads .caffemodel files through
generated classes.  This build needs only the weight-carrying subset —
NetParameter / LayerParameter / BlobProto / BlobShape — so a ~100-line
wire reader replaces the 1,500-line schema: no protoc step, no
third-party schema file, same bytes understood.

Field numbers (from the public caffe.proto schema, V2 'layer' format):
  NetParameter:   name=1 (string), layer=100 (repeated LayerParameter)
  LayerParameter: name=1, type=2 (strings), blobs=7 (repeated BlobProto)
  BlobProto:      data=5 (repeated float, usually packed),
                  shape=7 (BlobShape), legacy dims num=1 channels=2
                  height=3 width=4
  BlobShape:      dim=1 (repeated int64, usually packed)

The writer emits the same subset — used by tests to fabricate golden
.caffemodel fixtures offline (no caffe install exists here).
"""
import struct

__all__ = ["parse_caffemodel", "build_caffemodel"]


# ---------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------
def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(value):
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _scan(buf):
    """Yield (field_number, wire_type, value) over one message body.
    wt 0 -> int, wt 2 -> bytes, wt 5 -> 4 raw bytes, wt 1 -> 8 raw."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError("unsupported wire type %d" % wt)
        yield field, wt, val


def _field(tag, wt):
    return _write_varint((tag << 3) | wt)


def _len_delim(tag, payload):
    return _field(tag, 2) + _write_varint(len(payload)) + payload


# ---------------------------------------------------------------------
# reading .caffemodel
# ---------------------------------------------------------------------
def _parse_blob(buf):
    data = []
    dims = []
    legacy = {}
    for field, wt, val in _scan(buf):
        if field == 5:  # data: packed (wt2) or repeated fixed32 (wt5)
            if wt == 2:
                data.extend(struct.unpack("<%df" % (len(val) // 4), val))
            else:
                data.append(struct.unpack("<f", val)[0])
        elif field == 7 and wt == 2:  # shape: BlobShape{dim=1}
            for f2, wt2, v2 in _scan(val):
                if f2 == 1:
                    if wt2 == 2:  # packed varints
                        pos = 0
                        while pos < len(v2):
                            d, pos = _read_varint(v2, pos)
                            dims.append(d)
                    else:
                        dims.append(v2)
        elif field in (1, 2, 3, 4) and wt == 0:  # legacy NCHW dims
            legacy[field] = val
    if not dims and legacy:
        dims = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
    return {"shape": tuple(int(d) for d in dims), "data": data}


def _parse_layer(buf):
    layer = {"name": "", "type": "", "blobs": []}
    for field, wt, val in _scan(buf):
        if field == 1 and wt == 2:
            layer["name"] = val.decode("utf-8")
        elif field == 2 and wt == 2:
            layer["type"] = val.decode("utf-8")
        elif field == 7 and wt == 2:
            layer["blobs"].append(_parse_blob(val))
    return layer


def parse_caffemodel(data: bytes):
    """-> {"name": str, "layers": [{"name","type","blobs"}...]} (V2)."""
    net = {"name": "", "layers": []}
    for field, wt, val in _scan(data):
        if field == 1 and wt == 2:
            net["name"] = val.decode("utf-8")
        elif field == 100 and wt == 2:
            net["layers"].append(_parse_layer(val))
    return net


# ---------------------------------------------------------------------
# writing .caffemodel (test fixtures)
# ---------------------------------------------------------------------
def _build_blob(shape, values):
    body = b""
    dims = b"".join(_write_varint(int(d)) for d in shape)
    body += _len_delim(7, _len_delim(1, dims))
    payload = struct.pack("<%df" % len(values), *[float(v) for v in values])
    body += _len_delim(5, payload)
    return body


def build_caffemodel(name, layers):
    """layers: [(layer_name, layer_type, [(shape, flat_values), ...])]."""
    out = _len_delim(1, name.encode("utf-8"))
    for lname, ltype, blobs in layers:
        body = _len_delim(1, lname.encode("utf-8"))
        body += _len_delim(2, ltype.encode("utf-8"))
        for shape, values in blobs:
            body += _len_delim(7, _build_blob(shape, values))
        out += _len_delim(100, body)
    return out
