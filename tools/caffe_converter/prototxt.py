"""Prototxt text-format parser (the subset Caffe net definitions use).

The format is protobuf text: `key: value` scalars and `key { ... }`
nested blocks, repeated keys accumulating.  ~60 lines replace the
text_format.Merge + generated-schema path of the reference's
caffe_parser.py for the conversion use case.
"""
import re

__all__ = ["parse_prototxt"]

_TOKEN = re.compile(r"""
    (?P<comment>\#[^\n]*)
  | (?P<brace>[{}])
  | (?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*:?\s*
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<space>\s+)
""", re.X)


def _tokens(text):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ValueError("prototxt parse error at %r" % text[pos:pos + 40])
        pos = m.end()
        kind = m.lastgroup
        if kind in ("comment", "space"):
            continue
        yield kind, m.group().strip().rstrip(":").strip()


def _coerce(tok_kind, raw):
    if tok_kind == "string":
        return raw[1:-1]
    if tok_kind == "number":
        f = float(raw)
        return int(f) if f == int(f) and "." not in raw and "e" not in \
            raw.lower() else f
    # bare identifier: bool or enum name
    if raw in ("true", "True"):
        return True
    if raw in ("false", "False"):
        return False
    return raw


def parse_prototxt(text):
    """-> nested dict; repeated keys become lists."""
    stream = _tokens(text)

    def parse_block():
        block = {}

        def put(key, value):
            if key in block:
                if not isinstance(block[key], list):
                    block[key] = [block[key]]
                block[key].append(value)
            else:
                block[key] = value

        for kind, tok in stream:
            if kind == "brace" and tok == "}":
                return block
            if kind != "key":
                raise ValueError("expected key, got %r" % tok)
            key = tok
            kind2, tok2 = next(stream)
            if kind2 == "brace" and tok2 == "{":
                put(key, parse_block())
            else:
                put(key, _coerce(kind2, tok2))
        return block

    return parse_block()
