#!/usr/bin/env python
"""im2rec — pack an image folder (or .lst manifest) into RecordIO
(ref: tools/im2rec.py / tools/im2rec.cc).

Usage:
    python tools/im2rec.py prefix image_root [--list] [--recursive]
        [--quality 95] [--resize N] [--num-thread N]

With ``--list``, writes ``prefix.lst`` (``index\\tlabel\\tpath`` lines,
labels = per-subdirectory class ids, like the reference's list mode).
Without it, reads ``prefix.lst`` and writes ``prefix.rec`` + ``prefix.idx``.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png"}


def make_list(prefix, root, recursive=False):
    classes = []
    if recursive:
        for d in sorted(os.listdir(root)):
            if os.path.isdir(os.path.join(root, d)):
                classes.append(d)
    entries = []
    if classes:
        for label, d in enumerate(classes):
            for fn in sorted(os.listdir(os.path.join(root, d))):
                if os.path.splitext(fn)[1].lower() in _EXTS:
                    entries.append((label, os.path.join(d, fn)))
    else:
        for fn in sorted(os.listdir(root)):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                entries.append((0, fn))
    with open(prefix + ".lst", "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write("%d\t%f\t%s\n" % (i, float(label), rel))
    print("wrote %s (%d entries, %d classes)"
          % (prefix + ".lst", len(entries), max(1, len(classes))))


def make_rec(prefix, root, quality=95, resize=0):
    import numpy as np
    from PIL import Image

    from mxnet_tpu import recordio

    record = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(prefix + ".lst") as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, label, rel = int(parts[0]), float(parts[1]), parts[-1]
            try:
                im = Image.open(os.path.join(root, rel)).convert("RGB")
            except (OSError, ValueError):
                print("skipping unreadable %s" % rel, file=sys.stderr)
                continue
            if resize:
                w, h = im.size
                if h < w:
                    im = im.resize((int(w * resize / h), resize),
                                   Image.BICUBIC)
                else:
                    im = im.resize((resize, int(h * resize / w)),
                                   Image.BICUBIC)
            img = np.asarray(im)
            header = recordio.IRHeader(0, label, idx, 0)
            record.write_idx(idx, recordio.pack_img(header, img,
                                                    quality=quality))
            n += 1
    record.close()
    print("wrote %s.rec / %s.idx (%d records)" % (prefix, prefix, n))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true", help="generate .lst only")
    p.add_argument("--recursive", action="store_true",
                   help="per-subdirectory class labels")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--resize", type=int, default=0)
    args = p.parse_args()
    if args.list:
        make_list(args.prefix, args.root, args.recursive)
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root, True)
        make_rec(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
