"""Decompose the ImageRecordIter->train end-to-end rate into stages.

The round-2 bench reported 186 img/s end-to-end against 1,295+ img/s of
compute (io_vs_compute 0.144) without saying WHY.  This tool measures
each stage in isolation on the current backend so the bottleneck is a
number, not a guess (ref contract this pipeline must meet:
src/io/iter_image_recordio_2.cc:138-171 OMP decode pool +
src/io/iter_prefetcher.h:47 double-buffered prefetch):

  1. decode      - native pipeline rate, no Python copy, no device
  2. deliver     - decode + the Python-side view copy/cast (io.py next())
  3. h2d_link    - host->device bandwidth, float32 and uint8 batch sizes
  4. d2h_link    - device->host (the drain path)
  5. compute     - fused train step on device-resident data (bulk path)
  6. e2e         - the full overlapped pipeline as bench.py runs it

Prints one JSON dict.  Run with no args on the default backend (the
real chip under axon); on CPU it still decomposes decode/deliver.
"""
import json
import os
import sys
import tempfile
import time

import ctypes as ct

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_rec(n=256, size=256, tmp=None):
    from mxnet_tpu import recordio

    tmp = tmp or tempfile.mkdtemp(prefix="io_diag_")
    rec_path = os.path.join(tmp, "diag.rec")
    idx_path = os.path.join(tmp, "diag.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 1000), i, 0), img, quality=90))
    w.close()
    return rec_path, idx_path, n


def bench_decode_native(rec_path, idx_path, batch, threads, epochs=4):
    """Stage 1: pull batches straight off the C ring buffer, touch one
    byte, release.  No numpy copy, no cast, no device."""
    from mxnet_tpu import _native

    L = _native.lib()
    mean = (ct.c_float * 3)(0, 0, 0)
    std = (ct.c_float * 3)(1, 1, 1)
    h = ct.c_void_p()
    rc = L.MXTPUImageIterCreate(
        rec_path.encode(), idx_path.encode(), batch, 3, 224, 224,
        1, 1, 1, mean, std, threads, 0, 1, 0, 1, 4, ct.byref(h))
    assert rc == 0
    data_p = ct.POINTER(ct.c_float)()
    label_p = ct.POINTER(ct.c_float)()
    pad = ct.c_int()
    seen = 0
    t0 = time.time()
    for _ in range(epochs):
        L.MXTPUImageIterReset(h)
        while True:
            rc = L.MXTPUImageIterNext(h, ct.byref(data_p), ct.byref(label_p),
                                      ct.byref(pad))
            if rc != 1:
                break
            seen += batch
    dt = time.time() - t0
    L.MXTPUImageIterFree(h)
    return seen / dt


def bench_deliver(rec_path, idx_path, batch, threads, dtype, epochs=4):
    """Stage 2: the full Python iterator surface (copy + cast), no
    device."""
    from mxnet_tpu import io

    it = io.ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path,
        data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
        rand_crop=True, rand_mirror=True, preprocess_threads=threads,
        dtype=dtype)
    seen = 0
    t0 = time.time()
    for _ in range(epochs):
        it.reset()
        while True:
            try:
                b = it.next()
            except StopIteration:
                break
            seen += batch
    return seen / (time.time() - t0)


def _device_drain(x):
    return np.asarray(x).reshape(-1)[0]


def bench_link(batch, reps=12):
    """Stages 3+4: raw host<->device bandwidth at batch granularity."""
    import jax
    import jax.numpy as jnp

    out = {}
    for name, arr in [
            ("f32", np.random.rand(batch, 3, 224, 224).astype(np.float32)),
            ("u8", np.random.randint(0, 255, (batch, 3, 224, 224),
                                     dtype=np.uint8))]:
        nbytes = arr.nbytes
        d = jax.device_put(arr)  # warm
        _device_drain(d[0, 0, 0, :1])
        t0 = time.time()
        for _ in range(reps):
            d = jax.device_put(arr)
        _device_drain(d[0, 0, 0, :1])
        dt = time.time() - t0
        out["h2d_%s_MBps" % name] = round(nbytes * reps / dt / 1e6, 1)
        out["h2d_%s_batch_ms" % name] = round(dt / reps * 1e3, 2)
        # d2h: pull the whole batch back
        t0 = time.time()
        for _ in range(reps):
            host = np.asarray(d)
        dt = time.time() - t0
        out["d2h_%s_MBps" % name] = round(nbytes * reps / dt / 1e6, 1)
    return out


def bench_compute(batch, bulk_k=48, dtype=None):
    """Stage 5: fused train step on device-resident data."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.dp import FusedTrainStep
    from mxnet_tpu.parallel.mesh import make_mesh

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((1,), ("dp",), jax.devices()[:1])
    step = FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, learning_rate=0.05, momentum=0.9,
                          dtype=dtype)
    X = nd.random.uniform(shape=(batch, 3, 224, 224))
    y = nd.array(np.random.randint(0, 1000, batch).astype("float32"))
    losses = step.run_steps(X, y, steps=bulk_k)
    _device_drain(losses.asnumpy())
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        losses = step.run_steps(X, y, steps=bulk_k)
        _device_drain(losses.asnumpy())
        best = min(best, time.time() - t0)
    return batch * bulk_k / best, step


def main():
    batch = 32
    threads = int(os.environ.get("IO_DIAG_THREADS", "8"))
    out = {"batch": batch, "threads": threads}

    rec_path, idx_path, n = make_rec()
    out["decode_native_ips"] = round(
        bench_decode_native(rec_path, idx_path, batch, threads), 1)
    out["deliver_f32_ips"] = round(
        bench_deliver(rec_path, idx_path, batch, threads, "float32"), 1)
    out["deliver_u8_ips"] = round(
        bench_deliver(rec_path, idx_path, batch, threads, "uint8"), 1)

    import jax
    out["backend"] = jax.devices()[0].device_kind
    out.update(bench_link(batch))

    compute_ips, _ = bench_compute(batch)
    out["compute_f32_ips"] = round(compute_ips, 1)

    # stage 6: bench.py's own decomposed e2e row
    import bench as bench_mod
    out["bench_io_row"] = bench_mod.bench_recordio_input(
        compute_ips=compute_ips, compute_dtype="float32", batch=batch)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
