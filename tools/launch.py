#!/usr/bin/env python
"""Cluster launcher for dist_sync/dist_async training.

ref: tools/launch.py:30-80 (delegates to the dmlc-core tracker; the
local launcher spawns scheduler+servers+workers as processes on one
host — the mode tests/nightly/test_all.sh:55 uses). ssh/mpi/yarn modes
are out of scope for the TPU build: multi-host TPU jobs launch through
jax.distributed; this launcher covers the PS-compat path.

Usage:
    python tools/launch.py -n 2 [-s 1] python train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers: int, num_servers: int, cmd, env=None,
                 quiet_servers: bool = False):
    """Spawn scheduler + servers + workers locally; returns the worker
    exit codes. Server/scheduler processes are killed once all workers
    exit (they block in their serve loops otherwise)."""
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(_free_port()),
        "DMLC_NUM_SERVER": str(num_servers),
        "DMLC_NUM_WORKER": str(num_workers),
    })

    procs = []
    daemon = []

    def spawn(role, extra=None, keep_output=True):
        e = dict(base_env)
        e["DMLC_ROLE"] = role
        e.update(extra or {})
        if role == "worker":
            argv = list(cmd)
        else:
            # scheduler/server: run the PS node loop, not the user script
            argv = [sys.executable, "-c",
                    "import mxnet_tpu.kvstore_server as s; s.init()"]
        out = None if keep_output or not quiet_servers else \
            subprocess.DEVNULL
        return subprocess.Popen(argv, env=e, stdout=out, stderr=out)

    daemon.append(spawn("scheduler", keep_output=False))
    for _ in range(num_servers):
        daemon.append(spawn("server", keep_output=False))
    for i in range(num_workers):
        procs.append(spawn("worker", {"DMLC_WORKER_ID": str(i)}))

    # poll instead of blocking wait: one crashed worker would leave the
    # others stuck in a barrier forever, hanging the launcher
    import time

    grace_until = None
    while True:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            break
        if any(c is not None and c != 0 for c in codes):
            if grace_until is None:
                grace_until = time.time() + 15  # let healthy workers end
            elif time.time() > grace_until:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                codes = [p.wait() for p in procs]
                break
        time.sleep(0.1)
    for p in daemon:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.send_signal(signal.SIGTERM)
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
    return codes


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=None,
                    help="defaults to num-workers (like the reference)")
    ap.add_argument("--launcher", choices=["local"], default="local")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    ns = args.num_servers if args.num_servers is not None \
        else args.num_workers
    codes = launch_local(args.num_workers, ns, args.command)
    sys.exit(max(codes) if codes else 0)


if __name__ == "__main__":
    main()
