#!/usr/bin/env python
"""Cluster launcher for dist_sync/dist_async training.

ref: tools/launch.py:30-80 (delegates to the dmlc-core tracker: local,
ssh, mpi, yarn modes; the local launcher spawns scheduler+servers+
workers as processes on one host — the mode tests/nightly/test_all.sh:55
uses).

Modes here:
  * ``local`` — PS-compat path: scheduler + servers + workers on this
    host (DMLC_* env contract).
  * ``jax``   — TPU-native path: N ``jax.distributed`` controller
    processes on this host (MXNET_COORDINATOR_ADDRESS env contract);
    gradient exchange rides XLA collectives, no parameter server.
  * ``ssh``   — the multi-host version of ``jax``: one controller per
    host from ``--hostfile``, like the reference's ssh tracker
    (dmlc-core tracker ssh mode).

Usage:
    python tools/launch.py -n 2 [-s 1] python train.py --kv-store dist_sync
    python tools/launch.py -n 2 --launcher jax python train.py --kv-store tpu
    python tools/launch.py -n 16 --launcher ssh -H hosts python train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers: int, num_servers: int, cmd, env=None,
                 quiet_servers: bool = False):
    """Spawn scheduler + servers + workers locally; returns the worker
    exit codes. Server/scheduler processes are killed once all workers
    exit (they block in their serve loops otherwise)."""
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(_free_port()),
        "DMLC_NUM_SERVER": str(num_servers),
        "DMLC_NUM_WORKER": str(num_workers),
    })

    procs = []
    daemon = []

    def spawn(role, extra=None, keep_output=True):
        e = dict(base_env)
        e["DMLC_ROLE"] = role
        e.update(extra or {})
        if role == "worker":
            argv = list(cmd)
        else:
            # scheduler/server: run the PS node loop, not the user script
            argv = [sys.executable, "-c",
                    "import mxnet_tpu.kvstore_server as s; s.init()"]
        out = None if keep_output or not quiet_servers else \
            subprocess.DEVNULL
        return subprocess.Popen(argv, env=e, stdout=out, stderr=out)

    daemon.append(spawn("scheduler", keep_output=False))
    for _ in range(num_servers):
        daemon.append(spawn("server", keep_output=False))
    for i in range(num_workers):
        procs.append(spawn("worker", {"DMLC_WORKER_ID": str(i)}))

    # poll instead of blocking wait: one crashed worker would leave the
    # others stuck in a barrier forever, hanging the launcher
    import time

    grace_until = None
    while True:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            break
        if any(c is not None and c != 0 for c in codes):
            if grace_until is None:
                grace_until = time.time() + 15  # let healthy workers end
            elif time.time() > grace_until:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                codes = [p.wait() for p in procs]
                break
        time.sleep(0.1)
    for p in daemon:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.send_signal(signal.SIGTERM)
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
    return codes


def launch_jax(num_processes: int, cmd, env=None, hosts=None,
               coordinator_port=None):
    """Spawn ``jax.distributed`` controller processes — locally (one per
    process id) or one per host over ssh.  Process 0's host runs the
    coordination service; every process exports the MXNET_* contract
    consumed by ``mxnet_tpu.dist.initialize()``."""
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    if hosts:
        coord = "%s:%d" % (hosts[0], coordinator_port or 9123)
    else:
        coord = "127.0.0.1:%d" % (coordinator_port or _free_port())

    procs = []
    for pid in range(num_processes):
        e = dict(base_env)
        e.update({
            "MXNET_COORDINATOR_ADDRESS": coord,
            "MXNET_NUM_PROCESSES": str(num_processes),
            "MXNET_PROCESS_ID": str(pid),
        })
        if hosts:
            host = hosts[pid % len(hosts)]
            exports = " ".join(
                "%s=%s" % (k, _shquote(e[k]))
                for k in ("MXNET_COORDINATOR_ADDRESS",
                          "MXNET_NUM_PROCESSES", "MXNET_PROCESS_ID",
                          "PYTHONPATH") if k in e)
            # the PS shared secret rides stdin, NEVER the command line:
            # /proc/<pid>/cmdline is world-readable on the remote host
            secret = e.get("MXNET_PS_SECRET")
            prefix = ("IFS= read -r MXNET_PS_SECRET && "
                      "export MXNET_PS_SECRET && " if secret else "")
            remote = "%scd %s && env %s %s" % (
                prefix, _shquote(os.getcwd()), exports,
                " ".join(_shquote(c) for c in cmd))
            argv = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
            p = subprocess.Popen(argv, env=base_env,
                                 stdin=subprocess.PIPE if secret else None)
            if secret:
                p.stdin.write((secret + "\n").encode())
                p.stdin.close()
            procs.append(p)
        else:
            procs.append(subprocess.Popen(list(cmd), env=e))
    return [p.wait() for p in procs]


def _shquote(s):
    import shlex

    return shlex.quote(str(s))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=None,
                    help="defaults to num-workers (like the reference)")
    ap.add_argument("--launcher", choices=["local", "jax", "ssh"],
                    default="local")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line (ssh launcher)")
    ap.add_argument("--coordinator-port", type=int, default=None,
                    help="jax.distributed coordinator port (ssh/jax "
                         "launchers); default: free port locally, 9123 "
                         "over ssh")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "local":
        ns = args.num_servers if args.num_servers is not None \
            else args.num_workers
        codes = launch_local(args.num_workers, ns, args.command)
    else:
        hosts = None
        if args.launcher == "ssh":
            if not args.hostfile:
                ap.error("--launcher ssh needs --hostfile")
            with open(args.hostfile) as f:
                hosts = [ln.strip() for ln in f if ln.strip()]
        codes = launch_jax(args.num_workers, args.command, hosts=hosts,
                           coordinator_port=args.coordinator_port)
    sys.exit(max(codes) if codes else 0)


if __name__ == "__main__":
    main()
