#!/usr/bin/env python
"""Merge per-rank chrome traces into one multi-process timeline.

Multi-worker runs dump one ``profile_rank{K}.json`` per rank
(``mxnet_tpu.profiler`` stamps ``pid = rank``); chrome://tracing and
Perfetto render each pid as its own process lane, so merging is: load
every rank file, force each file's events onto its rank's pid, keep one
``process_name`` metadata row per rank, and concatenate.

Timestamps stay relative to each rank's own profiler start (the ranks'
clocks are not realigned — within a synchronized job the skew is the
barrier jitter, which is itself informative).

Usage:
    tools/merge_traces.py profile_rank0.json profile_rank1.json -o merged.json
    tools/merge_traces.py --self-test
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

_RANK_RE = re.compile(r"rank(\d+)")


def rank_of(path: str, payload: dict, fallback: int) -> int:
    """Rank for one input file: the ``rank{K}`` filename token wins,
    then the first event's pid, then the file's position."""
    m = _RANK_RE.search(os.path.basename(path))
    if m:
        return int(m.group(1))
    for ev in payload.get("traceEvents", []):
        if ev.get("ph") != "M" and "pid" in ev:
            return int(ev["pid"])
    return fallback


def merge(payloads):
    """[(path, payload)] -> one chrome-trace dict with per-rank pids."""
    merged = []
    seen_ranks = set()
    for idx, (path, payload) in enumerate(payloads):
        rank = rank_of(path, payload, idx)
        if rank in seen_ranks:
            raise ValueError("duplicate rank %d (file %s)" % (rank, path))
        seen_ranks.add(rank)
        lane = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
                 "args": {"name": "rank %d" % rank}}]
        for ev in payload.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the single rank label above
            lane.append(dict(ev, pid=rank))
        merged.extend(lane)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def merge_files(paths, out_path):
    payloads = []
    for p in paths:
        with open(p) as f:
            payloads.append((p, json.load(f)))
    result = merge(payloads)
    with open(out_path, "w") as f:
        json.dump(result, f)
    return result


def self_test() -> int:
    """Synthesize two rank dumps, merge, assert pid remapping."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        paths = []
        for rank in (0, 1):
            payload = {"traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": "stale"}},
                {"name": "dot", "cat": "operator", "ph": "X", "ts": 1.0,
                 "dur": 2.0, "pid": 0, "tid": 0},
                {"name": "kvstore:push_bytes", "cat": "comms", "ph": "C",
                 "ts": 3.0, "pid": 0, "tid": 0,
                 "args": {"kvstore:push_bytes": 64}},
            ], "displayTimeUnit": "ms"}
            p = os.path.join(d, "profile_rank%d.json" % rank)
            with open(p, "w") as f:
                json.dump(payload, f)
            paths.append(p)
        out = os.path.join(d, "merged.json")
        result = merge_files(paths, out)
        with open(out) as f:
            on_disk = json.load(f)
        assert on_disk == result
        events = result["traceEvents"]
        assert len(events) == 6, events
        pids = sorted({e["pid"] for e in events})
        assert pids == [0, 1], "pid remapping failed: %s" % pids
        for rank in (0, 1):
            names = [e["name"] for e in events if e["pid"] == rank]
            assert names.count("dot") == 1
            labels = [e["args"]["name"] for e in events
                      if e["pid"] == rank and e.get("ph") == "M"
                      and e["name"] == "process_name"]
            assert labels == ["rank %d" % rank], labels
    print("merge_traces self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="*",
                    help="per-rank trace JSON files (profile_rank{K}.json)")
    ap.add_argument("-o", "--output", default="profile_merged.json",
                    help="merged trace path (default: profile_merged.json)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in synthetic merge check and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if len(args.inputs) < 2:
        ap.error("need at least two rank traces to merge")
    result = merge_files(args.inputs, args.output)
    print("merged %d files, %d events -> %s"
          % (len(args.inputs), len(result["traceEvents"]), args.output))
    return 0


if __name__ == "__main__":
    sys.exit(main())
