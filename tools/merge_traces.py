#!/usr/bin/env python
"""Merge per-rank chrome traces into one multi-process timeline, and
(``--health``) diagnose a hung/desynced/slow fleet from per-rank
flight-recorder + trace dumps.

Multi-worker runs dump one ``profile_rank{K}.json`` per rank
(``mxnet_tpu.profiler`` stamps ``pid = rank``); chrome://tracing and
Perfetto render each pid as its own process lane, so merging is: load
every rank file, force each file's events onto its rank's pid, keep one
``process_name`` metadata row per rank, and concatenate.

Timestamps stay relative to each rank's own profiler start (the ranks'
clocks are not realigned — within a synchronized job the skew is the
barrier jitter, which is itself informative).

``--health`` ingests ``flightrecorder_rank{K}.json`` dumps
(``mxnet_tpu.diagnostics``, emitted on exit/SIGTERM/SIGUSR1/watchdog)
together with the rank traces and reports: the last collective seq each
rank completed, which ranks diverge and at exactly which seq/bucket/key
("rank 1 never entered seq 12"), collectives still in flight or marked
suspect by the watchdog, heartbeat-declared dead peers (each rank's
header carries the scheduler's dead_nodes answer), bucket-plan
mismatches between ranks, and per-rank step-time distributions with
slowest-rank / p50-vs-p99 straggler flags.  Dumps from an elastic
supervisor run are grouped by the header's generation counter and —
together with the supervisor's ``supervisor_events.json`` journal —
rendered as the RESTART TIMELINE ("gen 0: W=2, reached seq 12, rank 1
killed (exit 137); gen 1: W=1, resumed from step 4, completed"); the
desync/dead-peer verdict judges the NEWEST incarnation.  Exit code 2
when a desync, dead peer, plan mismatch or exhausted restart budget
was detected.

Serving request-trace dumps (``reqtrace_rank{K}.json`` —
mxnet_tpu/serving/reqtrace.py) join the same glob: ``--health`` adds a
SERVING section (per-model queue-wait p99, slot utilization, died
waiting vs died executing, and a stall scan over each dump's slowest
requests — chaos-injected stalls are labeled, never failed on), and
the plain merge mode lifts each dump's continuous-batching slot
timeline into its own process lane next to the training ranks.

Usage:
    tools/merge_traces.py profile_rank0.json profile_rank1.json -o merged.json
    tools/merge_traces.py --health flightrecorder_rank*.json profile_rank*.json
    tools/merge_traces.py --health reqtrace_rank*.json
    tools/merge_traces.py --self-test
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

_RANK_RE = re.compile(r"rank(\d+)")

#: serving slot-timeline lanes merge at pid 1000+rank so they never
#: collide with a training rank's pid in the same merged view
SERVING_PID_BASE = 1000


def rank_of(path: str, payload: dict, fallback: int) -> int:
    """Rank for one input file: the ``rank{K}`` filename token wins,
    then the first event's pid, then the file's position."""
    m = _RANK_RE.search(os.path.basename(path))
    if m:
        return int(m.group(1))
    for ev in payload.get("traceEvents", []):
        if ev.get("ph") != "M" and "pid" in ev:
            return int(ev["pid"])
    return fallback


def merge(payloads):
    """[(path, payload)] -> one chrome-trace dict with per-rank pids.

    Serving reqtrace dumps contribute their continuous-batching slot
    timeline as a ``serving rank K`` process lane (pid 1000+K) so slot
    churn renders next to the training ranks' lanes."""
    merged = []
    seen_ranks = set()
    for idx, (path, payload) in enumerate(payloads):
        if is_reqtrace_payload(payload):
            rank = int(payload["header"].get("rank", idx) or 0)
            pid = SERVING_PID_BASE + rank
            if pid in seen_ranks:
                raise ValueError("duplicate serving reqtrace rank %d "
                                 "(file %s)" % (rank, path))
            seen_ranks.add(pid)
            merged.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": "serving rank %d" % rank}})
            timeline = payload.get("slot_timeline") or {}
            for ev in timeline.get("traceEvents", []):
                if ev.get("ph") == "M" and \
                        ev.get("name") == "process_name":
                    continue
                merged.append(dict(ev, pid=pid))
            continue
        rank = rank_of(path, payload, idx)
        if rank in seen_ranks:
            raise ValueError("duplicate rank %d (file %s)" % (rank, path))
        seen_ranks.add(rank)
        lane = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
                 "args": {"name": "rank %d" % rank}}]
        for ev in payload.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the single rank label above
            lane.append(dict(ev, pid=rank))
        merged.extend(lane)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def merge_files(paths, out_path):
    payloads = []
    for p in paths:
        with open(p) as f:
            payloads.append((p, json.load(f)))
    result = merge(payloads)
    with open(out_path, "w") as f:
        json.dump(result, f)
    return result


# ---------------------------------------------------------------------
# --health: collective desync + straggler analysis over per-rank
# flight-recorder and trace dumps
# ---------------------------------------------------------------------
def is_flight_payload(payload: dict) -> bool:
    return bool(isinstance(payload, dict)
                and payload.get("header", {}).get("flight_recorder"))


def is_supervisor_payload(payload: dict) -> bool:
    """The elastic supervisor's events journal
    (mxnet_tpu/elastic/supervisor.py supervisor_events.json) —
    content-classified like the other dump families."""
    return bool(isinstance(payload, dict)
                and payload.get("elastic_supervisor"))


def is_traceview_payload(payload: dict) -> bool:
    """A traceview device-timeline summary
    (``traceview_summary_rank{K}.json`` — mxnet_tpu/traceview)."""
    return bool(isinstance(payload, dict)
                and payload.get("format")
                == "mxnet-tpu-traceview-summary")


def is_reqtrace_payload(payload: dict) -> bool:
    """A serving request-trace dump (``reqtrace_rank{K}.json`` —
    mxnet_tpu/serving/reqtrace.py)."""
    return bool(isinstance(payload, dict)
                and payload.get("header", {}).get("format")
                == "mxnet-tpu-reqtrace")


def load_health_inputs_ex(paths):
    """Split input files into ``(flight_by_gen, traces, supervisor,
    traceviews, reqtraces)``: ``flight_by_gen`` maps generation →
    {rank: flight_payload} (an elastic supervisor restarts the fleet
    with a bumped MXNET_ELASTIC_GENERATION, so the SAME rank dumps
    once per incarnation — duplicates are only an error within one
    generation), ``traces`` maps rank → trace payload, ``supervisor``
    is the supervisor's events journal (or None), ``traceviews`` maps
    rank → traceview device-timeline summary, ``reqtraces`` maps rank
    → serving request-trace dump."""
    flight_by_gen, traces, traceviews, reqtraces = {}, {}, {}, {}
    supervisor = None
    for idx, p in enumerate(paths):
        with open(p) as f:
            payload = json.load(f)
        if is_supervisor_payload(payload):
            supervisor = payload
        elif is_reqtrace_payload(payload):
            rank = int(payload["header"].get(
                "rank", rank_of(p, {}, idx)) or 0)
            if rank in reqtraces:
                raise ValueError("duplicate serving reqtrace rank %d "
                                 "(%s)" % (rank, p))
            reqtraces[rank] = payload
        elif is_flight_payload(payload):
            rank = int(payload["header"].get(
                "rank", rank_of(p, {}, idx)))
            gen = int(payload["header"].get("generation", 0) or 0)
            by_rank = flight_by_gen.setdefault(gen, {})
            if rank in by_rank:
                raise ValueError(
                    "duplicate flight-recorder rank %d in generation "
                    "%d (%s)" % (rank, gen, p))
            by_rank[rank] = payload
        elif is_traceview_payload(payload):
            rank = int(payload.get("rank", rank_of(p, {}, idx)) or 0)
            if rank in traceviews:
                raise ValueError("duplicate traceview summary rank %d "
                                 "(%s)" % (rank, p))
            traceviews[rank] = payload
        else:
            rank = rank_of(p, payload, idx)
            if rank in traces:
                raise ValueError("duplicate trace rank %d (%s)" % (rank, p))
            traces[rank] = payload
    return flight_by_gen, traces, supervisor, traceviews, reqtraces


def load_health_inputs(paths):
    """Compatibility surface: ({rank: flight_payload} for the NEWEST
    generation, {rank: trace_payload}).  Single-generation inputs (no
    supervisor in play) behave exactly as before."""
    flight_by_gen, traces, _sup, _tv, _rq = load_health_inputs_ex(paths)
    newest = max(flight_by_gen) if flight_by_gen else None
    return (flight_by_gen.get(newest, {}) if newest is not None
            else {}), traces


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _entry_brief(e):
    return {"seq": e.get("seq"), "op": e.get("op"),
            "bucket": e.get("bucket"), "keys": e.get("keys"),
            "bytes": e.get("bytes"), "dtype": e.get("dtype"),
            "state": e.get("state"),
            "injected": bool(e.get("injected"))}


def analyze_desync(flight):
    """Per-rank completion state + divergence: which rank stopped at
    which collective seq, described (op/bucket/keys) from a rank that
    DID complete it."""
    ranks = {}
    for rank, payload in sorted(flight.items()):
        entries = payload.get("entries", [])
        done = [e["seq"] for e in entries if e.get("state") == "completed"]
        stuck = [e for e in entries
                 if e.get("state") in ("in_flight", "suspect")]
        ranks[rank] = {
            "last_seq_completed": max(done) if done else -1,
            "next_seq": payload["header"].get("next_seq"),
            "n_entries": len(entries),
            "dropped": payload["header"].get("dropped", 0),
            "in_flight": [_entry_brief(e) for e in stuck],
            "suspect": [_entry_brief(e) for e in stuck
                        if e.get("state") == "suspect"],
        }
    if not ranks:
        return {"ranks": {}, "detected": False, "laggards": []}
    max_done = max(r["last_seq_completed"] for r in ranks.values())
    laggards = []
    for rank, info in sorted(ranks.items()):
        if info["last_seq_completed"] >= max_done:
            continue
        # the collective this rank never completed: what it was stuck
        # INSIDE if anything is in flight, else the one after its last
        # completion
        stalled = info["in_flight"][0] if info["in_flight"] else None
        stalled_seq = stalled["seq"] if stalled else \
            info["last_seq_completed"] + 1
        # describe the missing collective from a rank that completed it
        desc = stalled
        if desc is None:
            for other, payload in sorted(flight.items()):
                if other == rank:
                    continue
                match = [e for e in payload.get("entries", [])
                         if e.get("seq") == stalled_seq]
                if match:
                    desc = _entry_brief(match[0])
                    break
        laggards.append({
            "rank": rank, "stalled_at_seq": stalled_seq,
            "last_seq_completed": info["last_seq_completed"],
            "behind_by": max_done - info["last_seq_completed"],
            "collective": desc,
        })
    return {"ranks": ranks, "detected": bool(laggards),
            "max_completed_seq": max_done, "laggards": laggards}


def analyze_dead_peers(flight):
    """Heartbeat-declared dead peers: each rank's flight header carries
    the scheduler's dead_nodes answer (_ps.Heartbeat feeds it via
    diagnostics.set_dead_peers).  Reported as {peer: [ranks that saw it
    dead]} — a peer every surviving rank declares dead IS the hang's
    root cause, named directly instead of inferred from seq lag."""
    seen = {}
    for rank, payload in sorted(flight.items()):
        for peer in payload.get("header", {}).get("dead_peers") or []:
            seen.setdefault(str(peer), []).append(rank)
    return {"detected": bool(seen),
            "peers": {p: sorted(r) for p, r in sorted(seen.items())}}


def analyze_bucket_plans(flight):
    """Bucket-plan fingerprints per rank + mismatch detection — two
    ranks reducing under DIFFERENT plans desync by construction."""
    plans = {rank: payload["header"].get("bucket_plan")
             for rank, payload in sorted(flight.items())}
    fp = {rank: None if p is None else
          (p.get("n_buckets"), p.get("total_bytes"), p.get("cap_bytes"))
          for rank, p in plans.items()}
    stamped = {k: v for k, v in fp.items() if v is not None}
    return {"per_rank": plans,
            "mismatch": len(set(stamped.values())) > 1 if stamped else False}


def analyze_stragglers(traces, slow_factor: float = 1.25,
                       jitter_factor: float = 3.0):
    """Per-rank step-time distributions from the trace dumps.

    The step proxy is the complete-event ('ph':'X') span name present
    on EVERY rank with the largest total duration on rank 0 — on
    healthy dumps that is the per-step span family (Executor forward/
    backward, Module::update, KVStore::*).  Flags: a rank whose p50
    exceeds ``slow_factor`` x the fleet-median p50 is a straggler; a
    rank whose p99 exceeds ``jitter_factor`` x its own p50 has
    intermittent stalls.
    """
    if not traces:
        return None
    durs = {}
    for rank, payload in traces.items():
        by_name = {}
        for ev in payload.get("traceEvents", []):
            if ev.get("ph") == "X" and "dur" in ev:
                by_name.setdefault(ev["name"], []).append(float(ev["dur"]))
        durs[rank] = by_name
    common = set.intersection(*(set(d) for d in durs.values())) \
        if durs else set()
    if not common:
        return {"step_span": None, "note": "no span name common to all "
                "ranks", "per_rank": {}}
    rank0 = min(durs)
    proxy = max(common, key=lambda n: sum(durs[rank0][n]))
    per_rank = {}
    for rank, by_name in sorted(durs.items()):
        vals = sorted(by_name[proxy])
        per_rank[rank] = {
            "count": len(vals),
            "mean_ms": sum(vals) / len(vals) / 1e3,
            "p50_ms": _pct(vals, 0.50) / 1e3,
            "p99_ms": _pct(vals, 0.99) / 1e3,
            "max_ms": vals[-1] / 1e3,
        }
    p50s = sorted(r["p50_ms"] for r in per_rank.values())
    fleet_p50 = _pct(p50s, 0.5)
    slowest = max(per_rank, key=lambda r: per_rank[r]["p50_ms"])
    flagged = []
    for rank, st in per_rank.items():
        slow = fleet_p50 and st["p50_ms"] > slow_factor * fleet_p50
        jitter = st["p50_ms"] > 0 and \
            st["p99_ms"] > jitter_factor * st["p50_ms"]
        st["straggler"] = bool(slow)
        st["intermittent_stalls"] = bool(jitter)
        if slow or jitter:
            flagged.append(rank)
    return {"step_span": proxy, "fleet_p50_ms": fleet_p50,
            "slowest_rank": slowest, "flagged_ranks": sorted(flagged),
            "per_rank": per_rank}


def analyze_phase_skew(traceviews, slow_factor: float = 1.5):
    """Cross-rank skew over traceview device-timeline summaries: for
    every step phase and every reduce bucket, compare each rank's
    MEASURED per-step device seconds against the fleet median and name
    the outlier ("rank 2 spends 2.1x fleet-median in bucket 5
    reduce").  A rank whose summary recorded chaos-injected events is
    still reported, but its findings are tagged ``injected`` — an
    injected stall is the fault-injection campaign working, not a
    hardware straggler, and it never flips the health verdict."""
    if not traceviews:
        return None
    injected_ranks = {rank for rank, tv in traceviews.items()
                      if (tv.get("injected") or {}).get("events")}
    phases, buckets = {}, {}
    for rank, tv in sorted(traceviews.items()):
        for phase, v in (tv.get("phases") or {}).items():
            # parse.py emits per_step_s as the per-step LIST and mean_s
            # as the scalar; accept either shape (hand-rolled summaries
            # may carry a scalar per_step_s)
            s = v.get("mean_s")
            if s is None:
                s = v.get("per_step_s")
                if isinstance(s, (list, tuple)):
                    s = sum(s) / len(s) if s else None
            if s is not None:
                phases.setdefault(phase, {})[rank] = float(s)
        for b in tv.get("buckets") or []:
            s = b.get("device_s_per_step")
            if s is not None and b.get("bucket") is not None:
                buckets.setdefault(int(b["bucket"]), {})[rank] = float(s)
    findings = []

    def scan(kind, table):
        for key, per_rank in sorted(table.items()):
            if len(per_rank) < 2:
                continue
            med = _pct(sorted(per_rank.values()), 0.5)
            if not med:
                continue
            for rank, s in sorted(per_rank.items()):
                if s > slow_factor * med:
                    findings.append({
                        "rank": rank, "kind": kind,
                        kind: key, "per_step_s": s,
                        "fleet_median_s": med,
                        "factor": round(s / med, 2),
                        "injected": rank in injected_ranks})

    scan("phase", phases)
    scan("bucket", buckets)
    return {"n_ranks": len(traceviews),
            "injected_ranks": sorted(injected_ranks),
            "findings": findings,
            "detected": any(not f["injected"] for f in findings)}


def analyze_serving(reqtraces, stall_share: float = 0.5):
    """Serving-tier health over request-trace dumps: per-model
    queue-wait p99 / slot utilization / died-waiting-vs-executing
    aggregates, plus a stall scan over each dump's slowest requests —
    a request whose dominant phase is a ``stall:*`` phase above
    ``stall_share`` of its wall time is a finding.  Chaos-injected
    stalls (``stall:injected:*`` phases, spans tagged
    ``injected=true`` by the chaos hooks) are reported loudly but
    never flip the health verdict — a seeded ``stall_decode_tick`` is
    the fault-injection campaign working, not a capacity problem."""
    if not reqtraces:
        return None
    models = {}
    for _rank, payload in sorted(reqtraces.items()):
        for model, m in (payload.get("models") or {}).items():
            agg = models.setdefault(model, {
                "completed": 0, "rejected": 0, "cancelled": 0,
                "died_waiting": 0, "died_executing": 0,
                "queue_wait_p99_ms": None, "slot_utilization": None,
                "slots": None})
            for k in ("completed", "rejected", "cancelled",
                      "died_waiting", "died_executing"):
                agg[k] += int(m.get(k) or 0)
            # multi-rank worst-case view: the hottest rank's p99 and
            # utilization are the ones the SLO sees
            for k in ("queue_wait_p99_ms", "slot_utilization",
                      "slots"):
                v = m.get(k)
                if v is not None:
                    agg[k] = v if agg[k] is None else max(agg[k], v)
    findings = []
    for rank, payload in sorted(reqtraces.items()):
        for rec in payload.get("slowest") or []:
            phases = rec.get("phases") or {}
            if not phases:
                continue
            name = max(phases, key=lambda k: phases[k])
            total = float(rec.get("total_s") or 0.0) or \
                sum(phases.values()) or 1.0
            share = phases[name] / total
            if not name.startswith("stall:") or share < stall_share:
                continue
            findings.append({
                "rank": rank, "request_id": rec.get("id"),
                "model": rec.get("model"), "phase": name,
                "share": round(share, 3),
                "total_ms": round(total * 1e3, 3),
                "injected": bool(name.startswith("stall:injected")),
                "attribution": rec.get("attribution"),
            })
    return {"n_dumps": len(reqtraces), "models": models,
            "findings": findings,
            "detected": any(not f["injected"] for f in findings)}


def _merge_intervals(intervals):
    """Sorted union of (start, end) spans as a list of [start, end]."""
    merged = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1][1] = e
        else:
            merged.append([s, e])
    return merged


def _union_us(intervals):
    """Total covered microseconds of a list of (start, end) spans."""
    return sum(e - s for s, e in _merge_intervals(intervals))


def _overlap_us(ios, steps):
    """Microseconds of wall time covered by BOTH io and step spans —
    union-vs-union intersection, so concurrent io spans (two decode
    workers active at once) never double-count: the fraction of io
    time hidden behind the step stays <= 1."""
    a, b = _merge_intervals(ios), _merge_intervals(steps)
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def analyze_io_overlap(traces):
    """Input-pipeline lanes vs the compiled step, per rank: how much of
    the ``io:*`` span time (decode-worker lanes, device_put, wait)
    coincides with compiled-step spans.  ``prefetch_overlap_frac`` near
    1.0 = the async device stage genuinely hides H2D behind compute;
    large ``io:wait`` time = the consumer is input-bound (grow
    MXNET_IO_WORKERS)."""
    if not traces:
        return None
    out = {}
    for rank, payload in sorted(traces.items()):
        ios, steps = [], []
        by_name = {}
        for ev in payload.get("traceEvents", []):
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            name = str(ev.get("name", ""))
            iv = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
            if name.startswith("io:") or ev.get("cat") == "io":
                ios.append(iv)
                by_name.setdefault(name, 0.0)
                by_name[name] += iv[1] - iv[0]
            elif ev.get("cat") == "step" or "run_steps" in name:
                steps.append(iv)
        if not ios:
            continue
        io_us = _union_us(ios)
        step_us = _union_us(steps)
        ov = _overlap_us(ios, steps) if steps else 0.0
        out[rank] = {
            "n_io_spans": len(ios),
            "n_step_spans": len(steps),
            "io_ms": io_us / 1e3,
            "step_ms": step_us / 1e3,
            "io_overlap_ms": ov / 1e3,
            "prefetch_overlap_frac": round(ov / io_us, 3) if io_us else 0.0,
            "by_lane_ms": {n: round(v / 1e3, 3)
                           for n, v in sorted(by_name.items())},
        }
    return out or None


def bucket_timings(flight):
    """Per-rank per-bucket enqueue→complete durations from flight
    dumps — the autotuner's offline input
    (mxnet_tpu.autotune.from_bucket_timings).  Every collective verb
    that can carry gradient traffic is exported (bucket_reduce, push,
    allreduce); ``in_graph`` marks issue-schedule stamps whose
    durations are NOT wire time (the autotuner excludes them from
    bandwidth estimation), and each rank's stamped bucket plan rides
    along so the tuner can reconstruct the payload stream."""
    out = {"format": "bucket-timings", "version": 1, "ranks": {}}
    for rank, payload in sorted(flight.items()):
        rows = []
        for e in payload.get("entries", []):
            op = e.get("op")
            if op not in ("bucket_reduce", "push", "allreduce"):
                continue
            enq, comp = e.get("enqueue_ts"), e.get("complete_ts")
            dur = None
            if enq is not None and comp is not None:
                dur = float(comp) - float(enq)
            rows.append({
                "seq": e.get("seq"), "op": op,
                "bucket": e.get("bucket"), "bytes": e.get("bytes"),
                "dtype": e.get("dtype"), "state": e.get("state"),
                "enqueue_ts": enq, "complete_ts": comp,
                "duration_s": dur,
                "in_graph": bool((e.get("args") or {}).get("in_graph")),
            })
        out["ranks"][str(rank)] = {
            "bucket_plan": payload.get("header", {}).get("bucket_plan"),
            "timings": rows,
        }
    return out


def run_bucket_timings(paths, out_path=None) -> int:
    flight, _traces = load_health_inputs(paths)
    if not flight:
        print("no flight-recorder dumps among the inputs", file=sys.stderr)
        return 1
    payload = bucket_timings(flight)
    text = json.dumps(payload, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text)
        n = sum(len(r["timings"]) for r in payload["ranks"].values())
        print("bucket timings: %d rank(s), %d row(s) -> %s"
              % (len(payload["ranks"]), n, out_path))
    else:
        print(text)
    return 0


def analyze_generations(flight_by_gen, supervisor):
    """The elastic restart timeline: one row per fleet incarnation,
    assembled from the supervisor's events journal (world size, resume
    step, who died with what code) corroborated by the per-generation
    flight dumps (how far each incarnation's collectives got)."""
    gens = {}

    def row(gen):
        return gens.setdefault(int(gen), {
            "world_size": None, "resume_step": None,
            "failures": [], "reason": None, "outcome": None,
            "ranks_dumped": [], "max_completed_seq": None,
            "dead_peers": [], "quarantined": [],
        })

    for gen, by_rank in sorted((flight_by_gen or {}).items()):
        r = row(gen)
        r["ranks_dumped"] = sorted(by_rank)
        desync = analyze_desync(by_rank)
        r["max_completed_seq"] = desync.get("max_completed_seq")
        r["dead_peers"] = sorted(
            analyze_dead_peers(by_rank)["peers"])
    n_restarts = None
    exhausted = False
    for ev in (supervisor or {}).get("events", []):
        r = row(ev.get("generation", 0))
        kind = ev.get("kind")
        if kind == "launch":
            r["world_size"] = ev.get("world_size")
            r["resume_step"] = ev.get("resume_step")
        elif kind in ("worker_exit", "chaos_kill", "worker_hung"):
            if kind == "worker_exit" and ev.get("reason") == "ok":
                continue
            r["failures"].append(
                {"rank": ev.get("rank"), "kind": kind,
                 "exit_code": ev.get("exit_code"),
                 "reason": ev.get("reason")})
        elif kind == "slot_quarantined":
            # SDC: the fingerprint vote named the slot's machine
            # corrupt — permanently excluded, never rejoined
            r["quarantined"].append(ev.get("slot"))
        elif kind == "fleet_down":
            r["reason"] = ev.get("reason")
            r["outcome"] = "down"
        elif kind == "fleet_done":
            r["outcome"] = "done"
            n_restarts = ev.get("restarts", n_restarts)
        elif kind == "budget_exhausted":
            r["outcome"] = "budget_exhausted"
            exhausted = True
    return {"n_generations": len(gens),
            "restarted": len(gens) > 1,
            "n_restarts": n_restarts,
            "budget_exhausted": exhausted,
            "generations": {str(g): gens[g] for g in sorted(gens)}}


def health_report(flight, traces, flight_by_gen=None, supervisor=None,
                  traceviews=None, reqtraces=None):
    report = {"n_flight_dumps": len(flight), "n_trace_dumps": len(traces),
              "desync": analyze_desync(flight)}
    if flight:
        report["bucket_plans"] = analyze_bucket_plans(flight)
        report["dead_peers"] = analyze_dead_peers(flight)
    multi_gen = flight_by_gen and len(flight_by_gen) > 1
    if supervisor is not None or multi_gen:
        report["elastic"] = analyze_generations(flight_by_gen,
                                                supervisor)
    stragglers = analyze_stragglers(traces)
    if stragglers is not None:
        report["stragglers"] = stragglers
    io = analyze_io_overlap(traces)
    if io is not None:
        report["io_overlap"] = io
    skew = analyze_phase_skew(traceviews or {})
    if skew is not None:
        report["phase_skew"] = skew
    serving = analyze_serving(reqtraces or {})
    if serving is not None:
        report["serving"] = serving
    return report


def format_elastic(elastic):
    """The restart timeline — "gen 0 died at seq 12 (rank 1 killed);
    gen 1 resumed at W=1 from step 4"."""
    lines = ["RESTART TIMELINE: %d generation(s)%s"
             % (elastic["n_generations"],
                " — RESTART BUDGET EXHAUSTED"
                if elastic.get("budget_exhausted") else "")]
    for g, r in sorted(elastic["generations"].items(),
                       key=lambda kv: int(kv[0])):
        bits = []
        if r.get("world_size") is not None:
            bits.append("W=%d" % r["world_size"])
        if r.get("resume_step") is not None:
            bits.append("resumed from step %s" % r["resume_step"])
        if r.get("max_completed_seq") is not None:
            bits.append("reached seq %d" % r["max_completed_seq"])
        for f in r.get("failures", []):
            code = f.get("exit_code")
            bits.append("rank %s %s%s"
                        % (f.get("rank"),
                           f.get("reason") or f.get("kind"),
                           "" if code is None else " (exit %s)" % code))
        for peer in r.get("dead_peers", []):
            bits.append("dead peer %s" % peer)
        for slot in r.get("quarantined", []):
            bits.append("slot %s QUARANTINED (sdc)" % slot)
        if r.get("outcome") == "down":
            bits.append("died (%s)" % (r.get("reason") or "?"))
        elif r.get("outcome") == "done":
            bits.append("completed")
        elif r.get("outcome") == "budget_exhausted":
            bits.append("gave up (restart budget)")
        lines.append("  gen %s: %s" % (g, ", ".join(bits) or "no data"))
    return lines


def format_health(report):
    """Human-readable lines — the "rank 1 never entered seq 12" view."""
    lines = []
    if report.get("elastic"):
        lines.extend(format_elastic(report["elastic"]))
    desync = report["desync"]
    for rank, info in sorted(desync.get("ranks", {}).items()):
        lines.append(
            "rank %d: last completed collective seq %d (%d recorded, "
            "%d dropped, %d in flight)"
            % (rank, info["last_seq_completed"], info["n_entries"],
               info["dropped"], len(info["in_flight"])))
        for e in info["suspect"]:
            lines.append(
                "  rank %d SUSPECT (watchdog timeout): seq %s %s bucket=%s "
                "keys=%s" % (rank, e["seq"], e["op"], e["bucket"],
                             e["keys"]))
    if desync.get("detected"):
        for lag in desync["laggards"]:
            c = lag.get("collective") or {}
            where = c.get("op") or "collective"
            detail = []
            if c.get("bucket") is not None:
                detail.append("bucket %s" % c["bucket"])
            if c.get("keys"):
                detail.append("keys %s" % ",".join(map(str, c["keys"])))
            label = "INJECTED STALL (chaos)" if c.get("injected") \
                else "DESYNC"
            lines.append(
                "%s: rank %d never completed seq %d (%s%s) — "
                "fleet reached seq %d, rank is %d behind"
                % (label, lag["rank"], lag["stalled_at_seq"], where,
                   (", " + ", ".join(detail)) if detail else "",
                   desync["max_completed_seq"], lag["behind_by"]))
    elif desync.get("ranks"):
        lines.append("no desync: all ranks completed seq %d"
                     % desync["max_completed_seq"])
    dead = report.get("dead_peers", {})
    if dead.get("detected"):
        for peer, ranks in dead["peers"].items():
            lines.append(
                "DEAD PEER (heartbeat): %s — declared dead by rank%s %s"
                % (peer, "" if len(ranks) == 1 else "s",
                   ",".join(map(str, ranks))))
    if report.get("bucket_plans", {}).get("mismatch"):
        lines.append("BUCKET PLAN MISMATCH: ranks are reducing under "
                     "different bucket plans (see report.bucket_plans)")
    st = report.get("stragglers")
    if st and st.get("per_rank"):
        lines.append("step-time proxy span: %r (fleet p50 %.3f ms)"
                     % (st["step_span"], st["fleet_p50_ms"]))
        for rank, r in sorted(st["per_rank"].items()):
            flags = []
            if r.get("straggler"):
                flags.append("STRAGGLER")
            if r.get("intermittent_stalls"):
                flags.append("INTERMITTENT-STALLS")
            lines.append(
                "  rank %d: n=%d mean %.3f ms p50 %.3f ms p99 %.3f ms "
                "max %.3f ms%s"
                % (rank, r["count"], r["mean_ms"], r["p50_ms"],
                   r["p99_ms"], r["max_ms"],
                   (" [" + ",".join(flags) + "]") if flags else ""))
        lines.append("slowest rank: %d" % st["slowest_rank"])
    io = report.get("io_overlap")
    if io:
        for rank, r in sorted(io.items()):
            lines.append(
                "  rank %d io lanes: %d span(s), %.3f ms io, %.3f ms "
                "overlapping the compiled step (prefetch overlap %.1f%%)"
                % (rank, r["n_io_spans"], r["io_ms"],
                   r["io_overlap_ms"],
                   100.0 * r["prefetch_overlap_frac"]))
    skew = report.get("phase_skew")
    if skew:
        lines.append("device-timeline summaries: %d rank(s)"
                     % skew["n_ranks"])
        for f in skew["findings"]:
            where = ("bucket %d reduce" % f["bucket"]
                     if f["kind"] == "bucket" else f["phase"])
            head = "INJECTED STALL (chaos)" if f["injected"] \
                else "PHASE SKEW"
            lines.append(
                "%s: rank %d spends %.1fx fleet-median in %s "
                "(%.6fs vs %.6fs per step)%s"
                % (head, f["rank"], f["factor"], where,
                   f["per_step_s"], f["fleet_median_s"],
                   " — chaos-injected, not a hardware straggler"
                   if f["injected"] else ""))
        if not skew["findings"]:
            lines.append("no cross-rank phase skew")
    sv = report.get("serving")
    if sv:
        lines.append("serving request traces: %d dump(s)"
                     % sv["n_dumps"])
        for model, m in sorted(sv["models"].items()):
            bits = ["%d completed" % m["completed"]]
            if m["rejected"]:
                bits.append("%d rejected" % m["rejected"])
            if m["cancelled"]:
                bits.append("%d cancelled" % m["cancelled"])
            if m["queue_wait_p99_ms"] is not None:
                bits.append("queue-wait p99 %.1f ms"
                            % m["queue_wait_p99_ms"])
            if m["slot_utilization"] is not None:
                bits.append("slot utilization %.0f%%%s"
                            % (100.0 * m["slot_utilization"],
                               "" if not m["slots"]
                               else " of %d slot(s)" % m["slots"]))
            if m["died_waiting"] or m["died_executing"]:
                bits.append("died waiting %d / executing %d"
                            % (m["died_waiting"],
                               m["died_executing"]))
            lines.append("  model %s: %s" % (model, ", ".join(bits)))
        for f in sv["findings"]:
            head = "INJECTED STALL (chaos)" if f["injected"] \
                else "SERVING STALL"
            lines.append(
                "%s: request %s (model %s) spent %.0f%% of %.1f ms "
                "in %s%s"
                % (head, f["request_id"], f["model"],
                   100.0 * f["share"], f["total_ms"], f["phase"],
                   " — chaos-injected, not a capacity problem"
                   if f["injected"] else ""))
            if f.get("attribution"):
                lines.append("  %s" % f["attribution"])
    return lines


def run_health(paths, out_path=None) -> int:
    (flight_by_gen, traces, supervisor,
     traceviews, reqtraces) = load_health_inputs_ex(paths)
    # desync/dead-peer/plan analysis judges the NEWEST incarnation —
    # cross-generation seq comparison is meaningless by construction
    newest = max(flight_by_gen) if flight_by_gen else None
    flight = flight_by_gen.get(newest, {}) if newest is not None else {}
    report = health_report(flight, traces, flight_by_gen=flight_by_gen,
                           supervisor=supervisor,
                           traceviews=traceviews,
                           reqtraces=reqtraces)
    for line in format_health(report):
        print(line)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
        print("health report -> %s" % out_path)
    # bucket-plan mismatch is a desync by construction, and a
    # heartbeat-declared dead peer is a fleet failure even when the
    # dead rank left no dump to diverge from — same exit contract as a
    # seq divergence so script consumers catch all three.  The checks
    # judge the NEWEST incarnation: a fleet the supervisor already
    # restarted healthy IS healthy (the timeline still tells the
    # story) — unless the supervisor itself gave up (budget).
    # A lag whose stalled collective carries the chaos injected tag is
    # the fault-injection campaign working (satellite of the traceview
    # PR: the tag replaces timing heuristics) — report it loudly as
    # INJECTED STALL but do NOT fail health on it.
    desync_real = report["desync"].get("detected") and any(
        not (lag.get("collective") or {}).get("injected")
        for lag in report["desync"].get("laggards", []))
    unhealthy = desync_real or \
        report.get("bucket_plans", {}).get("mismatch") or \
        report.get("dead_peers", {}).get("detected") or \
        report.get("elastic", {}).get("budget_exhausted") or \
        report.get("phase_skew", {}).get("detected") or \
        report.get("serving", {}).get("detected")
    return 2 if unhealthy else 0


def self_test() -> int:
    """Synthesize two rank dumps, merge, assert pid remapping."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        paths = []
        for rank in (0, 1):
            payload = {"traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": "stale"}},
                {"name": "dot", "cat": "operator", "ph": "X", "ts": 1.0,
                 "dur": 2.0, "pid": 0, "tid": 0},
                {"name": "kvstore:push_bytes", "cat": "comms", "ph": "C",
                 "ts": 3.0, "pid": 0, "tid": 0,
                 "args": {"kvstore:push_bytes": 64}},
                # io-pipeline lanes: a decode span on a worker lane and
                # a device_put half-overlapping the compiled step
                {"name": "io:decode", "cat": "io", "ph": "X", "ts": 10.0,
                 "dur": 0.5, "pid": 0, "tid": 100},
                {"name": "io:device_put", "cat": "io", "ph": "X",
                 "ts": 10.5, "dur": 0.5, "pid": 0, "tid": 1},
                {"name": "FusedTrainStep.run_steps[k=1]", "cat": "step",
                 "ph": "X", "ts": 10.75, "dur": 1.0, "pid": 0, "tid": 0},
            ], "displayTimeUnit": "ms"}
            p = os.path.join(d, "profile_rank%d.json" % rank)
            with open(p, "w") as f:
                json.dump(payload, f)
            paths.append(p)
        out = os.path.join(d, "merged.json")
        result = merge_files(paths, out)
        with open(out) as f:
            on_disk = json.load(f)
        assert on_disk == result
        events = result["traceEvents"]
        assert len(events) == 12, events
        pids = sorted({e["pid"] for e in events})
        assert pids == [0, 1], "pid remapping failed: %s" % pids
        for rank in (0, 1):
            names = [e["name"] for e in events if e["pid"] == rank]
            assert names.count("dot") == 1
            labels = [e["args"]["name"] for e in events
                      if e["pid"] == rank and e.get("ph") == "M"
                      and e["name"] == "process_name"]
            assert labels == ["rank %d" % rank], labels

        # --health: rank 1's flight recorder stops one collective short
        # (and has one in flight) — the analysis must name rank 1, the
        # stalled seq and its bucket/keys
        def flight_dump(rank, n_done, in_flight=None, dead=None):
            entries = [{"seq": s, "op": "bucket_reduce", "bucket": s % 3,
                        "keys": ["w%d" % s], "bytes": 1024,
                        "dtype": "float32", "enqueue_ts": 100.0 + s,
                        "complete_ts": 100.5 + s, "state": "completed"}
                       for s in range(n_done)]
            if in_flight is not None:
                entries.append({"seq": in_flight, "op": "bucket_reduce",
                                "bucket": in_flight % 3,
                                "keys": ["w%d" % in_flight], "bytes": 1024,
                                "dtype": "float32",
                                "enqueue_ts": 100.0 + in_flight,
                                "complete_ts": None, "state": "suspect"})
            payload = {"header": {"flight_recorder": True, "rank": rank,
                                  "num_workers": 2, "capacity": 256,
                                  "next_seq": len(entries), "dropped": 0,
                                  "dead_peers": list(dead or []),
                                  "bucket_plan": {"n_buckets": 3,
                                                  "total_bytes": 3072,
                                                  "cap_bytes": 4 << 20}},
                       "entries": entries}
            p = os.path.join(d, "flightrecorder_rank%d.json" % rank)
            with open(p, "w") as f:
                json.dump(payload, f)
            return p

        f0 = flight_dump(0, 13, dead=["worker:1"])
        f1 = flight_dump(1, 12, in_flight=12)
        flight, traces = load_health_inputs([f0, f1] + paths)
        assert set(flight) == {0, 1} and set(traces) == {0, 1}
        report = health_report(flight, traces)
        desync = report["desync"]
        assert desync["detected"], report
        assert desync["max_completed_seq"] == 12
        (lag,) = desync["laggards"]
        assert lag["rank"] == 1 and lag["stalled_at_seq"] == 12, lag
        assert lag["collective"]["bucket"] == 0
        assert lag["collective"]["keys"] == ["w12"]
        assert not report["bucket_plans"]["mismatch"]
        # heartbeat-declared dead peers ride the header into the report
        assert report["dead_peers"]["detected"]
        assert report["dead_peers"]["peers"] == {"worker:1": [0]}
        text = "\n".join(format_health(report))
        assert "rank 1 never completed seq 12" in text, text
        assert "bucket 0" in text and "w12" in text, text
        assert "DEAD PEER (heartbeat): worker:1" in text, text
        # straggler flags over the synthetic traces: identical spans on
        # both ranks -> nobody flagged
        st = report["stragglers"]
        assert st["step_span"] == "dot" and st["flagged_ranks"] == [], st
        # io lanes: 1.0 ms of io spans, of which the device_put's
        # second half (0.25 ms) coincides with the compiled-step span
        io = report["io_overlap"]
        assert set(io) == {0, 1}, io
        r0 = io[0]
        assert r0["n_io_spans"] == 2 and r0["n_step_spans"] == 1, r0
        assert abs(r0["io_ms"] - 1.0e-3) < 1e-9, r0
        assert abs(r0["io_overlap_ms"] - 0.25e-3) < 1e-9, r0
        assert r0["prefetch_overlap_frac"] == 0.25, r0
        assert "io:decode" in r0["by_lane_ms"], r0
        text = "\n".join(format_health(report))
        assert "prefetch overlap 25.0%" in text, text
        # concurrent io lanes (two decode workers at once) must not
        # double-count: both fully inside one step span = 100%, not 200%
        assert _overlap_us([(0.0, 10.0), (2.0, 8.0)],
                           [(0.0, 10.0)]) == 10.0
        assert _union_us([(0.0, 10.0), (2.0, 8.0)]) == 10.0

        # --bucket-timings: the autotuner's offline export — per-rank
        # rows with enqueue→complete durations + the stamped plan
        bt_out = os.path.join(d, "bucket_timings.json")
        rc = run_bucket_timings([f0, f1], bt_out)
        assert rc == 0
        with open(bt_out) as f:
            bt = json.load(f)
        assert bt["format"] == "bucket-timings" and set(bt["ranks"]) == \
            {"0", "1"}, bt
        r0 = bt["ranks"]["0"]
        assert r0["bucket_plan"]["n_buckets"] == 3
        assert len(r0["timings"]) == 13, len(r0["timings"])
        row = r0["timings"][0]
        assert row["op"] == "bucket_reduce" and row["bucket"] == 0
        assert abs(row["duration_s"] - 0.5) < 1e-9, row
        assert row["in_graph"] is False
        # rank 1's in-flight suspect has no completion: duration None
        last = bt["ranks"]["1"]["timings"][-1]
        assert last["state"] == "suspect" and last["duration_s"] is None
        # the export round-trips into the autotuner's timing model
        try:
            from mxnet_tpu.autotune import timing as _at_timing
        except ImportError:
            _at_timing = None  # tool usable without the package on path
        if _at_timing is not None:
            tm = _at_timing.from_bucket_timings(bt, path=bt_out)
            assert tm.n_units == 3 and tm.total_bytes == 3072
            assert tm.recorded_cap_bytes == 4 << 20

        # --health with generations: gen 0's fleet died (rank 1
        # killed at seq 12), the supervisor reshaped 2->1 and gen 1
        # completed — one glob over both incarnations' dumps + the
        # supervisor journal yields the restart timeline, and the
        # health verdict judges the NEWEST (healthy) incarnation
        gen_dir = os.path.join(d, "gens")
        os.makedirs(gen_dir)

        def gen_flight(gen, rank, n_done, dead=None):
            payload = {"header": {"flight_recorder": True, "rank": rank,
                                  "num_workers": 2 - gen,
                                  "generation": gen,
                                  "capacity": 256, "next_seq": n_done,
                                  "dropped": 0,
                                  "dead_peers": list(dead or []),
                                  "bucket_plan": None},
                       "entries": [
                           {"seq": s, "op": "bucket_reduce",
                            "bucket": 0, "keys": ["w"], "bytes": 64,
                            "dtype": "float32",
                            "enqueue_ts": float(s),
                            "complete_ts": s + 0.5,
                            "state": "completed"}
                           for s in range(n_done)]}
            p = os.path.join(gen_dir, "g%d_flightrecorder_rank%d.json"
                             % (gen, rank))
            with open(p, "w") as f:
                json.dump(payload, f)
            return p

        g0a = gen_flight(0, 0, 13, dead=["worker:1"])
        g0b = gen_flight(0, 1, 12)
        g1a = gen_flight(1, 0, 40)
        sup_events = {
            "elastic_supervisor": True, "version": 1, "num_slots": 2,
            "events": [
                {"ts": 1.0, "generation": 0, "kind": "launch",
                 "world_size": 2, "slots": [0, 1], "resume_step": None},
                {"ts": 2.0, "generation": 0, "kind": "worker_exit",
                 "rank": 1, "slot": 1, "exit_code": 137,
                 "reason": "killed"},
                {"ts": 2.05, "generation": 0,
                 "kind": "slot_quarantined", "slot": 1,
                 "reason": "sdc"},
                {"ts": 2.1, "generation": 0, "kind": "fleet_down",
                 "reason": "killed", "failed_slots": [1],
                 "resume_step": 4},
                {"ts": 3.0, "generation": 1, "kind": "launch",
                 "world_size": 1, "slots": [0], "resume_step": 4},
                {"ts": 4.0, "generation": 1, "kind": "worker_exit",
                 "rank": 0, "slot": 0, "exit_code": 0, "reason": "ok"},
                {"ts": 4.1, "generation": 1, "kind": "fleet_done",
                 "restarts": 1},
            ]}
        sup_path = os.path.join(gen_dir, "supervisor_events.json")
        with open(sup_path, "w") as f:
            json.dump(sup_events, f)
        fbg, tr, sup, _tv, _rq = load_health_inputs_ex(
            [g0a, g0b, g1a, sup_path])
        assert set(fbg) == {0, 1} and set(fbg[0]) == {0, 1} \
            and set(fbg[1]) == {0}, fbg
        assert sup is not None and not tr
        report = health_report(fbg[1], tr, flight_by_gen=fbg,
                               supervisor=sup)
        el = report["elastic"]
        assert el["n_generations"] == 2 and el["restarted"], el
        assert el["n_restarts"] == 1 and not el["budget_exhausted"]
        g0 = el["generations"]["0"]
        assert g0["world_size"] == 2 and g0["max_completed_seq"] == 12
        assert g0["dead_peers"] == ["worker:1"]
        assert g0["failures"][0]["exit_code"] == 137
        assert g0["quarantined"] == [1], g0
        g1 = el["generations"]["1"]
        assert g1["world_size"] == 1 and g1["resume_step"] == 4
        assert g1["max_completed_seq"] == 39 and g1["outcome"] == "done"
        text = "\n".join(format_health(report))
        assert "RESTART TIMELINE: 2 generation(s)" in text, text
        assert "gen 0: W=2, reached seq 12" in text, text
        assert "rank 1 killed (exit 137)" in text, text
        assert "slot 1 QUARANTINED (sdc)" in text, text
        assert "gen 1: W=1, resumed from step 4" in text, text
        # newest incarnation is healthy -> exit 0 despite gen 0's death
        rc = run_health([g0a, g0b, g1a, sup_path])
        assert rc == 0, rc
        # the compat surface still answers with the NEWEST generation
        fl, _tr = load_health_inputs([g0a, g0b, g1a, sup_path])
        assert set(fl) == {0}, fl

        # --health over traceview summaries: rank 2 spends 2.1x the
        # fleet median in bucket 5's reduce — the skew analysis names
        # the rank AND the bucket from MEASURED device time
        def tv_summary(rank, slow=1.0, injected=0):
            return {
                "format": "mxnet-tpu-traceview-summary", "version": 1,
                "rank": rank, "workload": "FusedTrainStep",
                "steps": {"n": 3, "mean_s": 0.01},
                "phases": {
                    "backward": {"per_step_s": 0.004},
                    "bucket_reduce": {"per_step_s": 0.001 * slow},
                },
                "buckets": [
                    {"bucket": b, "device_s_per_step":
                     0.0002 * (slow if b == 5 else 1.0)}
                    for b in range(6)],
                "injected": {"events": injected,
                             "kinds": ["delay_collective"]
                             if injected else []},
            }

        tv_paths = []
        for rank in range(3):
            p = os.path.join(d, "traceview_summary_rank%d.json" % rank)
            with open(p, "w") as f:
                json.dump(tv_summary(rank, slow=2.1 if rank == 2
                                     else 1.0), f)
            tv_paths.append(p)
        _fbg, _tr2, _sup2, tvs, _rq = load_health_inputs_ex(tv_paths)
        assert set(tvs) == {0, 1, 2}, tvs
        skew = analyze_phase_skew(tvs)
        assert skew["detected"], skew
        kinds = {(f["kind"], f.get("bucket"), f["rank"])
                 for f in skew["findings"]}
        assert ("bucket", 5, 2) in kinds, skew["findings"]
        assert all(f["rank"] == 2 for f in skew["findings"])
        report = health_report({}, {}, traceviews=tvs)
        text = "\n".join(format_health(report))
        assert "rank 2 spends 2.1x fleet-median in bucket 5 reduce" \
            in text, text
        rc = run_health(tv_paths)
        assert rc == 2, rc  # a real straggler fails health
        # the SAME skew with the chaos injected tag: reported as an
        # INJECTED STALL, health verdict stays green
        with open(tv_paths[2], "w") as f:
            json.dump(tv_summary(2, slow=2.1, injected=4), f)
        _fbg, _tr2, _sup2, tvs, _rq = load_health_inputs_ex(tv_paths)
        skew = analyze_phase_skew(tvs)
        assert not skew["detected"] and skew["findings"], skew
        assert skew["injected_ranks"] == [2], skew
        text = "\n".join(format_health(
            health_report({}, {}, traceviews=tvs)))
        assert "INJECTED STALL (chaos): rank 2" in text, text
        assert "not a hardware straggler" in text, text
        rc = run_health(tv_paths)
        assert rc == 0, rc
        # an injected flight-recorder stall is labeled, not
        # misattributed: rank 1 stuck inside a chaos-delayed collective
        f1_inj = os.path.join(d, "inj_flightrecorder_rank1.json")
        with open(os.path.join(d, "flightrecorder_rank1.json")) as f:
            inj_payload = json.load(f)
        for e in inj_payload["entries"]:
            if e.get("state") == "suspect":
                e["injected"] = True
                e["injected_kind"] = "delay_collective"
        with open(f1_inj, "w") as f:
            json.dump(inj_payload, f)
        flight2, _ = load_health_inputs([f0, f1_inj])
        report2 = health_report(flight2, {})
        (lag2,) = report2["desync"]["laggards"]
        assert lag2["collective"]["injected"], lag2
        text2 = "\n".join(format_health(report2))
        assert "INJECTED STALL (chaos): rank 1 never completed seq 12" \
            in text2, text2

        # --health over a serving request-trace dump: the SERVING
        # section names the model's queue-wait p99 / slot utilization /
        # died-waiting split and flags the slowest request's dominant
        # stall — injected stalls labeled, never failed on
        def reqtrace_dump(injected):
            stall = ("stall:injected:stall_decode_tick" if injected
                     else "stall:cache_exhausted")
            slow_rec = {
                "id": "req-slow", "model": "gen", "kind": "generate",
                "outcome": "ok", "total_s": 0.5, "done_mono": 12.0,
                "phases": {"queue": 0.01, "prefill": 0.04,
                           stall: 0.4, "decode": 0.05},
                "events": {"decode_ticks": 12},
                "injected_any": bool(injected),
                "attribution": "request req-slow [ok, 500.0ms "
                               "total]: 400.0ms %s (80%%)" % stall}
            return {
                "header": {"format": "mxnet-tpu-reqtrace", "rank": 0,
                           "num_workers": 1, "capacity": 256,
                           "topk": 8, "window_s": 60.0, "begun": 14,
                           "finished": 14, "spans_dropped": 0},
                "slowest": [slow_rec], "recent": [slow_rec],
                "open": [],
                "models": {"gen": {
                    "completed": 9, "rejected": 1, "cancelled": 1,
                    "died_waiting": 2, "died_executing": 1,
                    "queue_wait_p99_ms": 3.2,
                    "slot_utilization": 0.74, "slots": 4}},
                "exemplars": {"gen": {"latency_s": {
                    "request_id": "req-slow", "value": 0.5,
                    "age_s": 1.0}}},
                "slot_timeline": {"traceEvents": [
                    {"ph": "M", "name": "process_name", "pid": 0,
                     "tid": 0, "args": {"name": "serving"}},
                    {"ph": "X", "pid": 0, "tid": 1, "name": "seq:g1",
                     "cat": "serving_slot", "ts": 0.0, "dur": 1000.0,
                     "args": {"model": "gen", "slot": 0}},
                    {"ph": "M", "name": "thread_name", "pid": 0,
                     "tid": 1, "args": {"name": "gen/slot0"}}]},
            }

        rq_path = os.path.join(d, "reqtrace_rank0.json")
        with open(rq_path, "w") as f:
            json.dump(reqtrace_dump(injected=False), f)
        _fbg, _tr3, _sup3, _tv3, rq = load_health_inputs_ex([rq_path])
        assert set(rq) == {0}, rq
        sv_report = health_report({}, {}, reqtraces=rq)
        sv = sv_report["serving"]
        assert sv["n_dumps"] == 1 and sv["detected"], sv
        gen = sv["models"]["gen"]
        assert gen["completed"] == 9 and gen["died_waiting"] == 2 \
            and gen["died_executing"] == 1, gen
        assert gen["queue_wait_p99_ms"] == 3.2
        assert gen["slot_utilization"] == 0.74 and gen["slots"] == 4
        (sf,) = sv["findings"]
        assert sf["phase"] == "stall:cache_exhausted" and \
            not sf["injected"] and sf["share"] == 0.8, sf
        sv_text = "\n".join(format_health(sv_report))
        assert "queue-wait p99 3.2 ms" in sv_text, sv_text
        assert "slot utilization 74% of 4 slot(s)" in sv_text, sv_text
        assert "died waiting 2 / executing 1" in sv_text, sv_text
        assert "SERVING STALL: request req-slow (model gen) spent " \
            "80% of 500.0 ms in stall:cache_exhausted" in sv_text, \
            sv_text
        rc = run_health([rq_path])
        assert rc == 2, rc  # an organic dominant stall fails health
        # the SAME stall chaos-injected: labeled, verdict stays green
        with open(rq_path, "w") as f:
            json.dump(reqtrace_dump(injected=True), f)
        _fbg, _tr3, _sup3, _tv3, rq = load_health_inputs_ex([rq_path])
        sv = analyze_serving(rq)
        assert sv["findings"] and not sv["detected"], sv
        sv_text = "\n".join(format_health(
            health_report({}, {}, reqtraces=rq)))
        assert "INJECTED STALL (chaos): request req-slow" in sv_text \
            and "not a capacity problem" in sv_text, sv_text
        rc = run_health([rq_path])
        assert rc == 0, rc
        # plain merge lifts the dump's slot timeline into a serving
        # lane (pid 1000+rank) next to the training ranks
        merged2 = merge_files([paths[0], rq_path],
                              os.path.join(d, "merged2.json"))
        pids = sorted({e["pid"] for e in merged2["traceEvents"]})
        assert pids == [0, SERVING_PID_BASE], pids
        sv_events = [e for e in merged2["traceEvents"]
                     if e["pid"] == SERVING_PID_BASE]
        assert any(e.get("name") == "seq:g1" and e.get("ph") == "X"
                   for e in sv_events), sv_events
        labels = [e["args"]["name"] for e in sv_events
                  if e.get("ph") == "M"
                  and e["name"] == "process_name"]
        assert labels == ["serving rank 0"], labels
    print("merge_traces self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="*",
                    help="per-rank trace JSON files (profile_rank{K}.json) "
                         "and/or flight-recorder dumps "
                         "(flightrecorder_rank{K}.json, --health mode) "
                         "and/or serving request-trace dumps "
                         "(reqtrace_rank{K}.json)")
    ap.add_argument("-o", "--output", default=None,
                    help="merged trace path (default: profile_merged.json)"
                         " / health-report JSON path (--health)")
    ap.add_argument("--health", action="store_true",
                    help="desync + straggler analysis over per-rank "
                         "flight-recorder and trace dumps; exit code 2 "
                         "when a desync is detected")
    ap.add_argument("--bucket-timings", action="store_true",
                    help="export per-rank per-bucket enqueue->complete "
                         "durations as JSON (the input python -m "
                         "mxnet_tpu.autotune --tune consumes)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in synthetic merge+health check "
                         "and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.health:
        if not args.inputs:
            ap.error("--health needs at least one rank dump")
        return run_health(args.inputs, args.output)
    if args.bucket_timings:
        if not args.inputs:
            ap.error("--bucket-timings needs at least one flight dump")
        return run_bucket_timings(args.inputs, args.output)
    if len(args.inputs) < 2:
        ap.error("need at least two rank traces to merge")
    if args.output is None:
        args.output = "profile_merged.json"
    result = merge_files(args.inputs, args.output)
    print("merged %d files, %d events -> %s"
          % (len(args.inputs), len(result["traceEvents"]), args.output))
    return 0


if __name__ == "__main__":
    sys.exit(main())
