"""mxlint — repo-wide AST lint for compiled-path hazards.

The jaxpr auditor (mxnet_tpu/analysis) checks programs that already
traced; mxlint catches the bug classes that live in the SOURCE and
only manifest as runtime symptoms the diagnostics layer counts after
the fact (recompile storms, config typos, hot-loop host syncs):

  MXL001 unregistered-env      read of a ``MXNET_*`` env var not
                               declared in mxnet_tpu/env.py — a typo'd
                               knob silently running on defaults
  MXL002 direct-env-read       ``MXNET_*`` read bypassing the
                               mxnet_tpu.env accessors (parsing/
                               truthiness drift between sites)
  MXL003 recompile-hazard      time/random/uuid call inside a traced
                               function: the value bakes into the
                               trace as a constant — every step gets
                               yesterday's timestamp, or the jit
                               retraces forever (the storms
                               diagnostics.recompile_stats() counts
                               after the fact)
  MXL004 host-sync-in-loop     ``.block_until_ready()`` / ``.item()``
                               / ``np.asarray`` / ``float()`` on
                               device values inside a loop: one
                               device->host sync per iteration
  MXL005 import-time-env-read  module-level env read: launchers that
                               inject env per worker after import are
                               silently ignored (knobs registered
                               ``import_time=True`` in env.py are
                               exempt — that contract is documented)
  MXL006 bare-except-collective  ``except:`` around a collective call
                               site: swallows the desync/timeout the
                               flight recorder needs to see (also
                               catches KeyboardInterrupt/SystemExit)
  MXL008 ad-hoc-exit-code      ``os._exit``/``sys.exit`` with a bare
                               nonzero NUMERIC LITERAL outside the
                               sanctioned exit-code sites
                               (diagnostics.py / elastic/ / serving/):
                               the exit-code taxonomy (83 preempted,
                               84 diverged, 85 watchdog-abort, 86
                               restart-budget, 87 sdc, 137 killed) is
                               LOAD-BEARING for the elastic
                               supervisor's failure classification —
                               a new code invented ad hoc silently
                               lands in the "crashed" bucket (or
                               worse, collides).  Exit through the
                               named constants (EXIT_*,
                               KILL_EXIT_CODE) or add the code to the
                               taxonomy first.
  MXL007 jax-in-decode-worker  jax/device call (``device_put``,
                               ``block_until_ready``, any ``jax.*``)
                               inside a decode-worker function: pool
                               workers are HOST-ONLY — under the
                               default fork start method a worker
                               touching the parent's initialized jax
                               runtime deadlocks, and device placement
                               belongs to the async device stage
                               (io_pipeline.py).  Worker functions are
                               those named ``*_worker_main`` /
                               ``*decode_worker*`` / ``*io_worker*``
                               and functions passed as ``iter_fn`` to
                               InputPipeline/ShardedDecodePool.
  MXL009 rogue-device-trace    direct ``jax.profiler.start_trace`` /
                               ``stop_trace`` / ``trace`` /
                               ``TraceAnnotation`` outside
                               mxnet_tpu/traceview/: the traceview
                               capture wrapper is the ONE sanctioned
                               XLA device-trace site — a second trace
                               session corrupts (or silently drops)
                               the armed capture, and ad-hoc
                               annotations bypass the step-window
                               naming the attribution walker keys on.
  MXL010 wallclock-in-serving  ``time.time()`` (or ``datetime.now``)
                               inside ``mxnet_tpu/serving/``: every
                               serving deadline, duration, and
                               reqtrace span is monotonic-clock by
                               contract — one wall-clock read mixed in
                               makes a deadline jump on NTP slew and
                               an autopsy attribute negative time.
                               ``time.monotonic()`` (or
                               ``perf_counter``) is required;
                               wall-clock is allowed only for dump/
                               artifact timestamps via an inline
                               ``# mxlint: disable=MXL010``.

Pure-AST: imports NOTHING from the package (the env registry is read
by parsing mxnet_tpu/env.py's ``register(...)`` calls), so it lints a
broken tree too.  Suppress one line with ``# mxlint: disable=MXL00X``
(or ``# noqa: MXL00X``); accept legacy findings in
``tools/mxlint_baseline.json``.  Exit 0 = clean (new findings only),
1 = new findings, 2 = usage error.

Run: ``python -m tools.mxlint [--json out.json] [paths...]``
      ``python -m tools.mxlint --self-test``
"""
from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV_PY = os.path.join(REPO, "mxnet_tpu", "env.py")
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "mxlint_baseline.json")
DEFAULT_TARGETS = ("mxnet_tpu",)

MXNET_RE = re.compile(r"^MXNET_[A-Z0-9_]+$")

CODES = {
    "MXL001": "unregistered MXNET_* env var (declare it in mxnet_tpu/env.py)",
    "MXL002": "MXNET_* env read bypasses the mxnet_tpu.env accessors",
    "MXL003": "recompile hazard: host-varying call inside a traced function",
    "MXL004": "host sync inside a loop body",
    "MXL005": "import-time env read (launcher env injection ignored)",
    "MXL006": "bare except around a collective call site",
    "MXL007": "jax/device call inside a decode-worker function "
              "(workers are host-only; the device stage owns placement)",
    "MXL008": "numeric-literal exit code outside the sanctioned exit "
              "sites (the 83-87/137 taxonomy is load-bearing for the "
              "supervisor — exit through the named constants)",
    "MXL009": "direct jax.profiler trace call outside "
              "mxnet_tpu/traceview/ (the one sanctioned device-trace "
              "capture site)",
    "MXL010": "wall-clock read in the serving tier (deadlines/"
              "durations are monotonic-clock by contract; "
              "time.monotonic() required — inline-disable only for "
              "dump timestamps)",
}

# the serving tier's clock discipline (MXL010): every deadline and
# duration is monotonic; wall-clock only via inline disable
SERVING_TIER_RE = re.compile(r"mxnet_tpu[/\\]serving[/\\]")
WALLCLOCK_CALLS = {("time", "time"), ("time", "time_ns"),
                   ("datetime", "now"), ("datetime", "utcnow")}

# files whose exit codes ARE the taxonomy: the documented contract
# lives there, everything else must exit through its named constants
SANCTIONED_EXIT_RE = re.compile(
    r"mxnet_tpu[/\\](diagnostics\.py$|elastic[/\\]|serving[/\\])")

# the ONE sanctioned jax.profiler device-trace site (MXL009)
SANCTIONED_TRACE_RE = re.compile(r"mxnet_tpu[/\\]traceview[/\\]")
# jax.profiler attributes that open/annotate an XLA device trace
TRACE_PROFILER_ATTRS = {"start_trace", "stop_trace", "trace",
                        "TraceAnnotation", "StepTraceAnnotation"}

# decode-worker entry points by naming convention
WORKER_NAME_RE = re.compile(r"(_worker_main$|decode_worker|io_worker)")
# pool constructors whose iter_fn argument runs inside workers
WORKER_POOL_CTORS = {"InputPipeline", "ShardedDecodePool"}
# calls that flag MXL007 inside a worker function
WORKER_FORBIDDEN_ATTRS = {"device_put", "block_until_ready"}
WORKER_FORBIDDEN_ROOTS = {"jax", "jnp"}

# functions whose callable argument is traced by jax
TRACE_ENTRY_ATTRS = {
    "jit", "shard_map", "checkpoint", "remat", "vjp", "value_and_grad",
    "grad", "scan", "while_loop", "cond", "pmap", "custom_vjp",
    "make_jaxpr",
}
# env-reading callables (attribute names)
ENV_READ_ATTRS = {
    "get", "getenv", "get_raw", "get_str", "get_int", "get_float",
    "get_bool", "env_int", "env_bool", "_env_int", "_env_float",
}
# receivers that mark an env accessor call as routed through the registry
ENV_MODULE_NAMES = {"env", "_env", "_envmod"}

HOST_VARYING = {
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("time", "time_ns"), ("time", "perf_counter_ns"),
    ("datetime", "now"), ("datetime", "utcnow"),
    ("os", "urandom"), ("uuid", "uuid4"), ("uuid", "uuid1"),
}
RANDOM_MODULES = {"random"}          # python's random.*; np.random.*
HOST_SYNC_ATTRS = {"block_until_ready", "item"}
HOST_SYNC_NP_FUNCS = {"asarray", "array"}
COLLECTIVE_TOKENS = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "push", "pull",
    "allreduce", "broadcast", "bucketed_reduce", "ring_allreduce_flat",
}


class LintFinding(dict):
    @property
    def fingerprint(self) -> str:
        # stable across line moves: file + code + enclosing scope +
        # normalized source snippet
        tag = "%s::%s::%s::%s" % (
            self["file"], self["code"], self["scope"],
            hashlib.sha1(self["snippet"].encode()).hexdigest()[:12])
        return tag


def registered_env_names(env_path: str = ENV_PY
                         ) -> Tuple[Set[str], Set[str]]:
    """(registered, import_time_ok) MXNET_* names, parsed statically
    from env.py's register(...) calls."""
    registered: Set[str] = set()
    import_ok: Set[str] = set()
    try:
        tree = ast.parse(open(env_path).read(), env_path)
    except (OSError, SyntaxError):
        return registered, import_ok
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "register" and node.args):
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        registered.add(first.value)
        for kw in node.keywords:
            if kw.arg == "import_time" and isinstance(kw.value,
                                                     ast.Constant) \
                    and kw.value.value:
                import_ok.add(first.value)
    return registered, import_ok


def _dotted(node: ast.AST) -> List[str]:
    """['np', 'random', 'normal'] for np.random.normal; [] if not a
    plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _name_nodes(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class ModuleLinter:
    def __init__(self, path: str, source: str, registered: Set[str],
                 import_ok: Set[str], is_env_py: bool):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.registered = registered
        self.import_ok = import_ok
        self.is_env_py = is_env_py
        self.findings: List[LintFinding] = []
        self.tree = ast.parse(source, path)
        self.traced_fns = self._collect_traced_fns()
        self.worker_fns = self._collect_worker_fns()
        self.sanctioned_exit = bool(
            SANCTIONED_EXIT_RE.search(os.path.abspath(path)))
        self.sanctioned_trace = bool(
            SANCTIONED_TRACE_RE.search(os.path.abspath(path)))
        self.serving_tier = bool(
            SERVING_TIER_RE.search(os.path.abspath(path)))

    # -- pass 1: which local functions get traced by jax? --------------
    def _collect_traced_fns(self) -> Set[str]:
        defined = {n.name for n in ast.walk(self.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        traced: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                chain = _dotted(node.func)
                if chain and chain[-1] in TRACE_ENTRY_ATTRS:
                    for arg in node.args:
                        traced |= _name_nodes(arg) & defined
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tokens = set(_dotted(dec)) if not isinstance(
                        dec, ast.Call) else set(_dotted(dec.func))
                    if isinstance(dec, ast.Call):
                        for a in ast.walk(dec):
                            tokens |= set(_dotted(a) if isinstance(
                                a, (ast.Attribute, ast.Name)) else [])
                    if tokens & TRACE_ENTRY_ATTRS:
                        traced.add(node.name)
        return traced

    # -- pass 1b: which local functions run inside decode workers? -----
    def _collect_worker_fns(self) -> Set[str]:
        defined = {n.name for n in ast.walk(self.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        workers = {n for n in defined if WORKER_NAME_RE.search(n)}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            if not chain or chain[-1] not in WORKER_POOL_CTORS:
                continue
            cands = list(node.args[:1]) + \
                [kw.value for kw in node.keywords if kw.arg == "iter_fn"]
            for arg in cands:
                workers |= _name_nodes(arg) & defined
        return workers

    # -- helpers -------------------------------------------------------
    def _suppressed(self, line: int, code: str) -> bool:
        if 1 <= line <= len(self.lines):
            text = self.lines[line - 1]
            m = re.search(r"#\s*(?:mxlint:\s*disable=|noqa:\s*)"
                          r"([A-Z0-9, ]+)", text)
            if m and code in m.group(1):
                return True
        return False

    def _add(self, node: ast.AST, code: str, message: str,
             scope: str) -> None:
        line = getattr(node, "lineno", 0)
        if self._suppressed(line, code):
            return
        try:
            snippet = ast.get_source_segment(self.source, node) or ""
        except Exception:
            snippet = ""
        snippet = " ".join(snippet.split())[:160]
        self.findings.append(LintFinding(
            file=os.path.relpath(self.path, REPO), line=line, code=code,
            scope=scope, message=message, snippet=snippet))

    # -- pass 2: walk with context -------------------------------------
    def run(self) -> List[LintFinding]:
        self._walk(self.tree, fn_stack=[], traced=False, loop_depth=0)
        return self.findings

    def _env_name_in_call(self, call: ast.Call) -> Optional[str]:
        for arg in call.args[:1]:
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str) \
                    and MXNET_RE.match(arg.value):
                return arg.value
        return None

    def _check_env_read(self, node: ast.AST, fn_stack: List[str]
                        ) -> None:
        """MXL001/002/005 on one potential env-read node."""
        scope = ".".join(fn_stack) or "<module>"
        name = None
        routed = False
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if not chain or chain[-1] not in ENV_READ_ATTRS:
                return
            name = self._env_name_in_call(node)
            routed = len(chain) >= 2 and chain[-2] in ENV_MODULE_NAMES
        elif isinstance(node, ast.Subscript):
            chain = _dotted(node.value)
            if chain[-1:] != ["environ"]:
                return
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
                    and MXNET_RE.match(sl.value):
                name = sl.value
        if name is None:
            return
        if name not in self.registered:
            self._add(node, "MXL001",
                      "read of unregistered env var %s" % name, scope)
        if not routed and not self.is_env_py:
            self._add(node, "MXL002",
                      "%s read via os.environ — route through "
                      "mxnet_tpu.env accessors" % name, scope)
        if not fn_stack and not self.is_env_py \
                and name not in self.import_ok:
            self._add(node, "MXL005",
                      "%s read at import time — read lazily or "
                      "register import_time=True with justification"
                      % name, scope)

    def _check_traced_call(self, node: ast.Call, fn_stack: List[str]
                           ) -> None:
        chain = _dotted(node.func)
        if len(chain) < 2:
            return
        scope = ".".join(fn_stack)
        pair = (chain[-2], chain[-1])
        if pair in HOST_VARYING or chain[0] in RANDOM_MODULES \
                or (len(chain) >= 3 and chain[-2] == "random"
                    and chain[0] in ("np", "numpy")):
            self._add(node, "MXL003",
                      "%s inside traced function %r: value is baked "
                      "into the trace as a constant (or forces a "
                      "retrace per call)" % (".".join(chain), scope),
                      scope)

    def _check_host_sync(self, node: ast.Call, fn_stack: List[str]
                         ) -> None:
        scope = ".".join(fn_stack) or "<module>"
        chain = _dotted(node.func)
        if not chain:
            return
        if chain[-1] in HOST_SYNC_ATTRS:
            self._add(node, "MXL004",
                      ".%s() inside a loop: one device->host sync per "
                      "iteration" % chain[-1], scope)
        elif len(chain) >= 2 and chain[0] in ("np", "numpy") \
                and chain[-1] in HOST_SYNC_NP_FUNCS:
            self._add(node, "MXL004",
                      "np.%s inside a loop: device->host transfer per "
                      "iteration" % chain[-1], scope)

    def _check_worker_call(self, node: ast.Call, fn_stack: List[str]
                           ) -> None:
        """MXL007: jax/device calls under a decode-worker function."""
        chain = _dotted(node.func)
        if not chain:
            return
        if chain[-1] in WORKER_FORBIDDEN_ATTRS \
                or chain[0] in WORKER_FORBIDDEN_ROOTS:
            self._add(node, "MXL007",
                      "%s inside decode-worker function %r — workers "
                      "are host-only (fork-safety + the device stage "
                      "owns placement)"
                      % (".".join(chain), ".".join(fn_stack)),
                      ".".join(fn_stack))

    def _check_exit_call(self, node: ast.Call, fn_stack: List[str]
                         ) -> None:
        """MXL008: ``os._exit(<literal>)``/``sys.exit(<literal>)`` with
        a nonzero int outside the sanctioned exit-code sites.  Named
        constants (EXIT_PREEMPTED, KILL_EXIT_CODE, ...) and
        ``sys.exit(main())`` pass — the point is that new CODES enter
        the taxonomy deliberately, not that exits are forbidden."""
        if self.sanctioned_exit:
            return
        chain = _dotted(node.func)
        if chain[-2:] not in (["os", "_exit"], ["sys", "exit"]):
            return
        if not node.args:
            return
        a = node.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, int) \
                and not isinstance(a.value, bool) and a.value != 0:
            self._add(node, "MXL008",
                      "%s(%d): numeric-literal exit code outside the "
                      "sanctioned sites — the 83-87/137 taxonomy "
                      "drives the elastic supervisor; exit through a "
                      "named constant" % (".".join(chain), a.value),
                      ".".join(fn_stack) or "<module>")

    def _check_trace_call(self, node: ast.Call, fn_stack: List[str]
                          ) -> None:
        """MXL009: ``jax.profiler.start_trace/stop_trace/trace/
        TraceAnnotation`` outside mxnet_tpu/traceview/.  The capture
        wrapper there is the one sanctioned device-trace site — route
        through ``traceview.capture`` (or ``traceview.step_window``)
        so a second profiler session can never corrupt an armed
        capture."""
        if self.sanctioned_trace:
            return
        chain = _dotted(node.func)
        if len(chain) < 3 or chain[-3] != "jax" \
                or chain[-2] != "profiler" \
                or chain[-1] not in TRACE_PROFILER_ATTRS:
            return
        self._add(node, "MXL009",
                  "%s: direct jax.profiler trace call outside "
                  "mxnet_tpu/traceview/ — route through "
                  "traceview.capture (the one sanctioned device-trace "
                  "site)" % ".".join(chain),
                  ".".join(fn_stack) or "<module>")

    def _check_wallclock_call(self, node: ast.Call,
                              fn_stack: List[str]) -> None:
        """MXL010: wall-clock reads in mxnet_tpu/serving/.  A deadline
        computed from ``time.time()`` jumps under NTP slew and cannot
        be compared against the monotonic enqueue/done stamps the rest
        of the tier records."""
        if not self.serving_tier:
            return
        chain = _dotted(node.func)
        if tuple(chain[-2:]) not in WALLCLOCK_CALLS:
            return
        self._add(node, "MXL010",
                  "%s() in the serving tier — deadlines/durations are "
                  "monotonic-clock by contract; use time.monotonic() "
                  "(inline-disable only for dump timestamps)"
                  % ".".join(chain),
                  ".".join(fn_stack) or "<module>")

    def _check_bare_except(self, node: ast.Try, fn_stack: List[str]
                           ) -> None:
        scope = ".".join(fn_stack) or "<module>"
        bare = [h for h in node.handlers if h.type is None]
        if not bare:
            return
        tokens: Set[str] = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    chain = _dotted(sub.func)
                    if chain:
                        tokens.add(chain[-1])
        if tokens & COLLECTIVE_TOKENS:
            self._add(bare[0], "MXL006",
                      "bare `except:` around collective call(s) %s — "
                      "swallows the desync/timeout evidence (and "
                      "KeyboardInterrupt)"
                      % sorted(tokens & COLLECTIVE_TOKENS), scope)

    def _walk(self, node: ast.AST, fn_stack: List[str], traced: bool,
              loop_depth: int, worker: bool = False) -> None:
        for child in ast.iter_child_nodes(node):
            c_stack, c_traced, c_loop = fn_stack, traced, loop_depth
            c_worker = worker
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_stack = fn_stack + [child.name]
                c_traced = traced or child.name in self.traced_fns
                # nested defs inherit worker scope: they run in-process
                c_worker = worker or child.name in self.worker_fns
                c_loop = 0  # a new function body is a new loop scope
            elif isinstance(child, (ast.For, ast.While)):
                c_loop = loop_depth + 1
            if isinstance(child, (ast.Call, ast.Subscript)):
                self._check_env_read(child, fn_stack)
            if isinstance(child, ast.Call):
                if traced:
                    self._check_traced_call(child, fn_stack)
                if loop_depth > 0 and not traced:
                    self._check_host_sync(child, fn_stack)
                if worker:
                    self._check_worker_call(child, fn_stack)
                self._check_exit_call(child, fn_stack)
                self._check_trace_call(child, fn_stack)
                self._check_wallclock_call(child, fn_stack)
            if isinstance(child, ast.Try):
                self._check_bare_except(child, fn_stack)
            self._walk(child, c_stack, c_traced, c_loop, c_worker)


def lint_paths(paths: Sequence[str], registered: Set[str],
               import_ok: Set[str]) -> List[LintFinding]:
    findings: List[LintFinding] = []
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        else:
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                files += [os.path.join(root, n) for n in sorted(names)
                          if n.endswith(".py")]
    for path in sorted(files):
        try:
            src = open(path).read()
        except OSError:
            continue
        is_env_py = os.path.abspath(path) == os.path.abspath(ENV_PY)
        try:
            linter = ModuleLinter(path, src, registered, import_ok,
                                  is_env_py)
        except SyntaxError as exc:
            findings.append(LintFinding(
                file=os.path.relpath(path, REPO),
                line=getattr(exc, "lineno", 0) or 0, code="MXL000",
                scope="<module>", message="syntax error: %s" % exc,
                snippet=""))
            continue
        findings += linter.run()
    return findings


def load_baseline(path: str) -> Set[str]:
    try:
        with open(path) as f:
            return set(json.load(f).get("fingerprints", []))
    except (OSError, ValueError):
        return set()


# ---------------------------------------------------------------------------
SELF_TEST_SRC = '''
import os, sys, time, random
import numpy as np
import jax

K = os.environ.get("MXNET_NOT_A_REAL_KNOB", "0")          # 001/002/005

def build():
    cap = int(os.environ.get("MXNET_KVSTORE_BUCKET_BYTES", 4))  # 002

    def step(x):
        seed = time.time()                                 # 003
        noise = random.random()                            # 003
        return x * seed + noise

    return jax.jit(step)

def drain(vals):
    out = []
    for v in vals:
        out.append(np.asarray(v))                          # 004
        v.block_until_ready()                              # 004
    return out

def reduce_all(x):
    try:
        return jax.lax.psum(x, "dp")
    except:                                                # 006
        return x

def _decode_worker_main(q):
    x = q.get()
    jax.device_put(x)                                      # 007
    x.block_until_ready()                                  # 007

def my_iter_factory(num_parts=1, part_index=0):
    import jax.numpy as jnp
    return jnp.zeros(())                                   # 007 (iter_fn)

def start_pool():
    return InputPipeline(my_iter_factory, num_workers=2)

def give_up():
    sys.exit(86)                                           # 008

def rogue_trace(d):
    jax.profiler.start_trace(d)                            # 009
EXIT_CUSTOM = 99
def die_hard(ok):
    if ok:
        sys.exit(0)           # literal 0 is fine (success)
    if os.environ.get("X"):
        sys.exit(EXIT_CUSTOM)  # named constant: deliberate taxonomy
    os._exit(87)                                           # 008
'''

EXPECT_SELF_TEST = {"MXL001": 1, "MXL002": 2, "MXL003": 2, "MXL004": 2,
                    "MXL005": 1, "MXL006": 1, "MXL007": 3, "MXL008": 2,
                    "MXL009": 1}

# MXL010 is path-gated to mxnet_tpu/serving/ — its fixture lints under
# a serving-tier path (the main fixture stays outside, so the counts
# above are unaffected)
SERVING_SELF_TEST_SRC = '''
import time

def offer(req, deadline_s):
    t0 = time.time()                                       # 010
    req.deadline = time.time() + deadline_s                # 010
    ok = time.monotonic() - t0
    stamp = time.time()  # mxlint: disable=MXL010
    return ok, stamp
'''

EXPECT_SERVING_SELF_TEST = {"MXL010": 2}


def self_test() -> int:
    registered, import_ok = registered_env_names()
    if not registered:
        print("mxlint self-test FAILED: no names parsed from env.py")
        return 1
    if "MXNET_KVSTORE_BUCKET_BYTES" not in registered:
        print("mxlint self-test FAILED: registry parse missed a knob")
        return 1
    linter = ModuleLinter("<selftest>.py", SELF_TEST_SRC, registered,
                          import_ok, is_env_py=False)
    counts: Dict[str, int] = {}
    for f in linter.run():
        counts[f["code"]] = counts.get(f["code"], 0) + 1
    bad = {c: (counts.get(c, 0), want)
           for c, want in EXPECT_SELF_TEST.items()
           if counts.get(c, 0) != want}
    if bad:
        print("mxlint self-test FAILED: got!=want per code:", bad,
              "all:", counts)
        return 1
    if counts.get("MXL010"):
        print("mxlint self-test FAILED: MXL010 fired outside "
              "mxnet_tpu/serving/ (path gate broken):", counts)
        return 1
    sv = ModuleLinter("mxnet_tpu/serving/<selftest>.py",
                      SERVING_SELF_TEST_SRC, registered, import_ok,
                      is_env_py=False)
    sv_counts: Dict[str, int] = {}
    for f in sv.run():
        sv_counts[f["code"]] = sv_counts.get(f["code"], 0) + 1
    if sv_counts != EXPECT_SERVING_SELF_TEST:
        print("mxlint self-test FAILED: serving-tier fixture "
              "got!=want:", sv_counts, "want:",
              EXPECT_SERVING_SELF_TEST)
        return 1
    n_seed = sum(EXPECT_SELF_TEST.values()) + \
        sum(EXPECT_SERVING_SELF_TEST.values())
    n_codes = len(EXPECT_SELF_TEST) + len(EXPECT_SERVING_SELF_TEST)
    print("mxlint self-test OK: %d seeded findings across %d codes, "
          "%d env vars in registry" % (n_seed, n_codes,
                                       len(registered)))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="AST lint for compiled-path hazards (see module "
                    "docstring for codes)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: mxnet_tpu/)")
    ap.add_argument("--json", help="write findings JSON here")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline file (review the diff!)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()

    registered, import_ok = registered_env_names()
    paths = args.paths or [os.path.join(REPO, t)
                           for t in DEFAULT_TARGETS]
    findings = lint_paths(paths, registered, import_ok)
    if args.update_baseline:
        with open(args.baseline, "w") as fh:
            json.dump({"note": "accepted mxlint findings; regenerate "
                               "with --update-baseline and review",
                       "fingerprints": sorted(
                           {f.fingerprint for f in findings})}, fh,
                      indent=1)
            fh.write("\n")
        print("mxlint: baseline updated with %d fingerprint(s) -> %s"
              % (len(findings), args.baseline))
        return 0
    baseline = set() if args.no_baseline else load_baseline(
        args.baseline)
    new = [f for f in findings if f.fingerprint not in baseline]
    suppressed = len(findings) - len(new)
    for f in sorted(new, key=lambda f: (f["file"], f["line"])):
        print("%s:%d %s %s  [%s]" % (f["file"], f["line"], f["code"],
                                     f["message"], f["scope"]))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump({"n_findings": len(new),
                       "n_suppressed": suppressed,
                       "findings": [dict(f, fingerprint=f.fingerprint)
                                    for f in new]}, fh, indent=1)
    print("mxlint: %d new finding(s), %d baseline-suppressed, "
          "%d file(s) with findings" % (len(new), suppressed,
                                        len({f['file'] for f in findings})
                                        if findings else 0))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
