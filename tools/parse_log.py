#!/usr/bin/env python
"""Parse training logs into per-epoch tables
(ref: tools/parse_log.py — same log grammar: the fit loop's
"Epoch[N] Batch [M] Speed: S samples/sec metric=V" lines from
Speedometer, plus Train-/Validation- metric and Time cost lines).

    python tools/parse_log.py train.log
"""
from __future__ import annotations

import argparse
import re
import sys


# metric values can be negative, exponent-formatted, or nan/inf
_VAL_PAT = r"([-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?|[-+]?(?:nan|inf))"
_BATCH = re.compile(
    r"Epoch\[(\d+)\].*?Batch \[(\d+)\]\s*Speed:\s*([\d.]+) samples/sec"
    r"\s*(\w[\w-]*)=" + _VAL_PAT)
_TRAIN = re.compile(r"Epoch\[(\d+)\] Train-(\w[\w-]*)=" + _VAL_PAT)
_VAL = re.compile(r"Epoch\[(\d+)\] Validation-(\w[\w-]*)=" + _VAL_PAT)
_TIME = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.]+)")


def parse(lines):
    """→ dict epoch → {speed: [..], train: {m: v}, val: {m: v},
    time: s}."""
    epochs = {}

    def ep(i):
        return epochs.setdefault(int(i), {"speed": [], "train": {},
                                          "val": {}, "time": None})

    for line in lines:
        m = _BATCH.search(line)
        if m:
            ep(m.group(1))["speed"].append(float(m.group(3)))
            continue
        m = _TRAIN.search(line)
        if m:
            ep(m.group(1))["train"][m.group(2)] = float(m.group(3))
            continue
        m = _VAL.search(line)
        if m:
            ep(m.group(1))["val"][m.group(2)] = float(m.group(3))
            continue
        m = _TIME.search(line)
        if m:
            ep(m.group(1))["time"] = float(m.group(2))
    return epochs


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=["markdown", "csv"],
                    default="markdown")
    args = ap.parse_args()
    with open(args.logfile) as f:
        epochs = parse(f)
    if not epochs:
        print("no epochs found", file=sys.stderr)
        sys.exit(1)
    metrics = sorted({m for e in epochs.values()
                      for m in list(e["train"]) + list(e["val"])})
    header = ["epoch", "speed(avg)"] + \
        ["train-" + m for m in metrics] + \
        ["val-" + m for m in metrics] + ["time(s)"]
    sep = "," if args.format == "csv" else " | "
    print(sep.join(header))
    if args.format == "markdown":
        print(sep.join("---" for _ in header))
    for i in sorted(epochs):
        e = epochs[i]
        speed = (sum(e["speed"]) / len(e["speed"])) if e["speed"] else 0.0
        row = [str(i), "%.1f" % speed]
        row += ["%.5f" % e["train"][m] if m in e["train"] else ""
                for m in metrics]
        row += ["%.5f" % e["val"][m] if m in e["val"] else ""
                for m in metrics]
        row.append("%.1f" % e["time"] if e["time"] is not None else "")
        print(sep.join(row))


if __name__ == "__main__":
    main()
