#!/usr/bin/env python
"""serve_loadgen.py — drive open-loop load against the serving tier
and print the outcome accounting as JSON.

Two modes over an in-process demo server (the serving layer is what's
being measured; swap in a real checkpoint with --ckpt-dir):

  # fixed-rate window: offered/admitted/ok/shed/p50/p99
  python tools/serve_loadgen.py --qps 500 --duration 3

  # SLO ramp: the BENCH row — QPS sustained at a fixed p99 SLO
  python tools/serve_loadgen.py --slo-p99-ms 50

Chaos composes exactly like training: MXNET_CHAOS="slow_request:
model=demo,ms=5,count=1000000" reproduces the overload e2e from the
command line.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for mxnet_tpu.serving")
    ap.add_argument("--qps", type=float, default=200.0,
                    help="offered request rate (fixed-rate mode)")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="window seconds (fixed-rate mode)")
    ap.add_argument("--deadline-ms", type=float, default=1000.0,
                    help="per-request deadline")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="run the qps_at_slo ramp instead of one window")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--queue-max", type=int, default=128)
    ap.add_argument("--batch-deadline-ms", type=float, default=2.0)
    ap.add_argument("--ckpt-dir", default=None,
                    help="serve this elastic checkpoint's params "
                         "through the demo MLP apply_fn (dims must "
                         "match) instead of the fixed-seed weights")
    args = ap.parse_args(argv)

    from mxnet_tpu import serving

    if args.ckpt_dir:
        rt = serving.ModelRuntime.from_checkpoint(
            "demo", args.ckpt_dir, _demo_apply(),
            sample_shape=(16,), max_batch=args.max_batch)
    else:
        rt = serving.demo_runtime(max_batch=args.max_batch)
    srv = serving.ModelServer(max_batch=args.max_batch,
                              queue_max=args.queue_max,
                              batch_deadline_ms=args.batch_deadline_ms,
                              default_deadline_ms=args.deadline_ms)
    srv.add_model(rt)
    if args.slo_p99_ms is not None:
        out = serving.qps_at_slo(srv, rt.name,
                                 slo_p99_ms=args.slo_p99_ms)
    else:
        out = serving.run_load(srv, rt.name, qps=args.qps,
                               duration_s=args.duration)
    srv.drain()
    print(json.dumps(out, indent=2))
    return 0


def _demo_apply():
    def apply_fn(p, aux, x):
        import jax.numpy as jnp

        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.argmax(h @ p["w2"] + p["b2"], axis=-1)

    return apply_fn


if __name__ == "__main__":
    sys.exit(main())
